"""Int8 weight-only quantized serving: accuracy vs dequantized weights,
memory halving, and the full engine path."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestQuantizedLlama:
    def test_forward_close_to_dequantized(self, jax):
        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantize.quantize_llama(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 128)

        out_q = llama.forward(qparams, tokens, cfg, attn_impl="xla")
        # ground truth: the SAME quantization error but materialized weights
        deq = dict(params)
        deq["layers"] = {
            n: (
                quantize.dequantize_weight(w, dtype=params["layers"][n].dtype)
                if isinstance(w, quantize.QuantizedWeight)
                else w
            )
            for n, w in qparams["layers"].items()
        }
        deq["lm_head"] = quantize.dequantize_weight(
            qparams["lm_head"], dtype=params["lm_head"].dtype
        )
        out_deq = llama.forward(deq, tokens, cfg, attn_impl="xla")
        np.testing.assert_allclose(
            np.asarray(out_q), np.asarray(out_deq), atol=1e-3, rtol=1e-3
        )

    def test_memory_halves(self, jax):
        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="bfloat16",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantize.quantize_llama(params)
        dense_bytes = quantize.param_bytes(
            {"layers": {k: v for k, v in params["layers"].items() if v.ndim == 3}}
        )
        q_bytes = quantize.param_bytes(
            {
                "layers": {
                    k: v.q
                    for k, v in qparams["layers"].items()
                    if isinstance(v, quantize.QuantizedWeight)
                }
            }
        )
        assert q_bytes < dense_bytes * 0.6  # int8 vs bf16 + small scales

    def test_engine_int8_generates(self, jax):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(32,), quantization="int8", seed=0,
        )
        try:
            out = eng.generate("quantized", SamplingParams(max_tokens=4, temperature=0.0))
            assert isinstance(out, str)
        finally:
            eng.stop()

    def test_paged_decode_matches_forward_quantized(self, jax):
        """The serving decode path must stay exact vs forward under int8."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        qparams = quantize.quantize_llama(
            llama.init_params(jax.random.PRNGKey(0), cfg)
        )
        B, S = 1, 32
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, 128)
        logits_full = llama.forward(qparams, tokens, cfg, attn_impl="xla")

        page_size, pages_per_seq = 16, 4
        shape = (cfg.n_layers, 1 + B * pages_per_seq, page_size, cfg.n_kv_heads, cfg.head_dim)
        k_pages = jnp.zeros(shape, jnp.float32)
        v_pages = jnp.zeros_like(k_pages)
        pt = (1 + jnp.arange(B * pages_per_seq, dtype=jnp.int32)).reshape(B, -1)
        seq_lens = jnp.array([S - 1])
        logits_pf, k_pages, v_pages = llama.prefill(
            qparams, tokens, k_pages, v_pages, pt, seq_lens, cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits_pf[0]), np.asarray(logits_full[0, S - 2]), atol=2e-3
        )
