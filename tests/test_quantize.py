"""Int8 weight-only quantized serving: accuracy vs dequantized weights,
memory halving, and the full engine path."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestQuantizedLlama:
    def test_forward_close_to_dequantized(self, jax):
        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantize.quantize_llama(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 128)

        out_q = llama.forward(qparams, tokens, cfg, attn_impl="xla")
        # ground truth: the SAME quantization error but materialized weights
        deq = dict(params)
        deq["layers"] = {
            n: (
                quantize.dequantize_weight(w, dtype=params["layers"][n].dtype)
                if isinstance(w, quantize.QuantizedWeight)
                else w
            )
            for n, w in qparams["layers"].items()
        }
        deq["lm_head"] = quantize.dequantize_weight(
            qparams["lm_head"], dtype=params["lm_head"].dtype
        )
        out_deq = llama.forward(deq, tokens, cfg, attn_impl="xla")
        np.testing.assert_allclose(
            np.asarray(out_q), np.asarray(out_deq), atol=1e-3, rtol=1e-3
        )

    def test_memory_halves(self, jax):
        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="bfloat16",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantize.quantize_llama(params)
        dense_bytes = quantize.param_bytes(
            {"layers": {k: v for k, v in params["layers"].items() if v.ndim == 3}}
        )
        q_bytes = quantize.param_bytes(
            {
                "layers": {
                    k: v.q
                    for k, v in qparams["layers"].items()
                    if isinstance(v, quantize.QuantizedWeight)
                }
            }
        )
        assert q_bytes < dense_bytes * 0.6  # int8 vs bf16 + small scales

    def test_engine_int8_generates(self, jax):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(32,), quantization="int8", seed=0,
        )
        try:
            out = eng.generate("quantized", SamplingParams(max_tokens=4, temperature=0.0))
            assert isinstance(out, str)
        finally:
            eng.stop()

    def test_paged_decode_matches_forward_quantized(self, jax):
        """The serving decode path must stay exact vs forward under int8."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, dtype="float32",
        )
        qparams = quantize.quantize_llama(
            llama.init_params(jax.random.PRNGKey(0), cfg)
        )
        B, S = 1, 32
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, 128)
        logits_full = llama.forward(qparams, tokens, cfg, attn_impl="xla")

        page_size, pages_per_seq = 16, 4
        shape = (cfg.n_layers, 1 + B * pages_per_seq, page_size, cfg.n_kv_heads, cfg.head_dim)
        k_pages = jnp.zeros(shape, jnp.float32)
        v_pages = jnp.zeros_like(k_pages)
        pt = (1 + jnp.arange(B * pages_per_seq, dtype=jnp.int32)).reshape(B, -1)
        seq_lens = jnp.array([S - 1])
        logits_pf, k_pages, v_pages = llama.prefill(
            qparams, tokens, k_pages, v_pages, pt, seq_lens, cfg
        )
        np.testing.assert_allclose(
            np.asarray(logits_pf[0]), np.asarray(logits_full[0, S - 2]), atol=2e-3
        )


class TestInt4:
    def test_forward_close_to_dequantized(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, max_seq_len=64,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        qparams = quantize.quantize_llama(params, bits=4)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
        out_q = llama.forward(qparams, tokens, cfg, attn_impl="xla")
        deq = dict(qparams)
        deq["layers"] = {
            n: (
                quantize.dequantize_weight(w, dtype=params["layers"][n].dtype)
                if isinstance(w, quantize.QuantizedWeight)
                else w
            )
            for n, w in qparams["layers"].items()
        }
        deq["lm_head"] = quantize.dequantize_weight(
            qparams["lm_head"], dtype=params["lm_head"].dtype
        )
        out_d = llama.forward(deq, tokens, cfg, attn_impl="xla")
        # the two paths differ only in rounding ORDER (mm scales the f32
        # accumulator; dequant rounds w*scale to bf16 before the matmul) —
        # int4's larger scales amplify it, so compare in distribution
        a, b = np.asarray(out_q, np.float32), np.asarray(out_d, np.float32)
        denom = np.maximum(np.abs(b).max(), 1e-6)
        assert np.abs(a - b).max() / denom < 0.05
        assert np.mean(np.abs(a - b)) / denom < 0.005

    def test_int4_bytes_quarter_of_bf16(self, jax):
        from modal_examples_tpu.models import llama, quantize

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=128, max_seq_len=64,
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        q4 = quantize.quantize_llama(params, bits=4)
        q8 = quantize.quantize_llama(params, bits=8)
        matmul_bytes_bf16 = sum(
            v.size * v.dtype.itemsize
            for n, v in params["layers"].items()
            if n in quantize.LLAMA_TARGETS
        )
        b4 = sum(
            (v.q.size + 1) // 2
            for n, v in q4["layers"].items()
            if isinstance(v, quantize.QuantizedWeight)
        )
        b8 = sum(
            v.q.size
            for n, v in q8["layers"].items()
            if isinstance(v, quantize.QuantizedWeight)
        )
        assert b4 * 2 == b8  # int4 is half of int8
        assert b8 * 2 == matmul_bytes_bf16  # int8 is half of bf16
        # param_bytes accounts the packing
        assert quantize.param_bytes(q4) < quantize.param_bytes(q8)

    def test_engine_int4_generates_deterministically(self, jax):
        """int4 engine must generate, and greedy decode through the paged
        serving path must equal the dense forward's argmax continuation on
        the SAME int4 tree (the decode==forward exactness proof under
        int4 — the analog of test_paged_decode_matches_forward_quantized).
        """
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama, quantize
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(jax.random.PRNGKey(3), cfg)
        p = SamplingParams(max_tokens=6, temperature=0.0)
        eng = LLMEngine(
            cfg, params=params, max_slots=2, max_model_len=64, page_size=16,
            prefill_buckets=(32,), quantization="int4", seed=0,
        )
        req = eng.submit("hello world", p)
        out = "".join(eng.stream(req))
        qparams = eng.params  # the engine's own int4 tree

        seq = list(eng.tokenizer.encode("hello world"))
        got = []
        for _ in range(6):
            logits = llama.forward(
                qparams, jnp.asarray([seq], jnp.int32), cfg, attn_impl="xla"
            )
            nxt = int(jnp.argmax(logits[0, -1]))
            if nxt == eng.tokenizer.eos_id:
                break
            got.append(nxt)
            seq.append(nxt)
        want = eng.tokenizer.decode(got)
        eng.stop()
        assert out == want, (out, want)
        assert out  # this prompt generates non-empty text at these weights

    def test_host_load_int4_matches_device_quant(self, jax):
        """quantize_weight_host(bits=4) must produce the same quantized
        values as the device-side quantize_weight(bits=4)."""
        import numpy as np_

        from modal_examples_tpu.models import quantize

        w = np_.random.RandomState(0).randn(32, 16).astype(np_.float32)
        import jax.numpy as jnp

        host = quantize.quantize_weight_host(w, bits=4)
        dev = quantize.quantize_weight(jnp.asarray(w), bits=4)
        np.testing.assert_array_equal(
            np.asarray(host.q).astype(np.int8),
            np.asarray(dev.q).astype(np.int8),
        )
        np.testing.assert_allclose(
            np.asarray(host.scale), np.asarray(dev.scale), rtol=1e-6
        )
