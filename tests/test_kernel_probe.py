"""Wedge-proof kernel bring-up harness (VERDICT r4 #2).

Proves (1) the probe subprocess harness isolates hangs/crashes with a hard
kill, (2) every Pallas-kernel module has a registered probe so new kernels
cannot skip the harness, (3) a real kernel probe runs green end to end
through the subprocess path (interpreter mode on CPU; the same call
Mosaic-compiles on a chip).
"""

from __future__ import annotations

import re
import time
from pathlib import Path

import pytest

from modal_examples_tpu.ops.probes import KERNEL_PROBES
from modal_examples_tpu.utils import kernel_probe

OPS_DIR = Path(__file__).resolve().parent.parent / "modal_examples_tpu" / "ops"


class TestHarness:
    def test_ok_target(self):
        r = kernel_probe.run_probe(
            "modal_examples_tpu.utils.kernel_probe:_selftest_ok",
            timeout_s=120,
        )
        assert r.ok and r.status == "ok"
        assert r.payload == {"answer": 42}

    def test_failure_is_reported_not_raised(self):
        r = kernel_probe.run_probe(
            "modal_examples_tpu.utils.kernel_probe:_selftest_fail",
            timeout_s=120,
        )
        assert r.status == "fail"
        assert "deliberate numeric failure" in r.error

    def test_crash_is_contained(self):
        r = kernel_probe.run_probe(
            "modal_examples_tpu.utils.kernel_probe:_selftest_crash",
            timeout_s=120,
        )
        assert r.status == "crash"
        assert "exit code 3" in r.error

    def test_hang_is_killed_within_deadline(self):
        t0 = time.time()
        r = kernel_probe.run_probe(
            "modal_examples_tpu.utils.kernel_probe:_selftest_hang",
            timeout_s=3,
        )
        assert r.status == "timeout"
        # SIGKILL of the process group, not a polite wait: well under the
        # time a wedge would need to hold the claim
        assert time.time() - t0 < 30

    def test_sequence_stops_on_timeout(self):
        # timeout must outlive interpreter startup on a loaded machine
        # (the ok probe has to actually complete) while keeping the hang
        # probe's kill quick enough for the fast tier
        results = kernel_probe.run_probes(
            [
                "modal_examples_tpu.utils.kernel_probe:_selftest_ok",
                "modal_examples_tpu.utils.kernel_probe:_selftest_hang",
                "modal_examples_tpu.utils.kernel_probe:_selftest_fail",
            ],
            timeout_s=20,
        )
        statuses = [r.status for r in results.values()]
        # the post-timeout probe must NOT have run: the chip claim may be
        # wedged and another toucher would hang the same way
        assert statuses == ["ok", "timeout"]

    def test_unknown_registry_name_rejected(self):
        with pytest.raises(KeyError):
            kernel_probe.resolve_target("definitely_not_a_kernel")


class TestRegistryCoverage:
    def test_every_pallas_module_has_a_probe(self):
        """New kernels must route first compiles through the harness: any
        module calling pl.pallas_call needs an entry in PROBED_MODULES
        (mapping module -> its probe names) and those probes registered."""
        from modal_examples_tpu.ops.probes import PROBED_MODULES

        pkg_root = OPS_DIR.parent
        pallas_modules = set()
        for f in pkg_root.rglob("*.py"):
            if f.name == "probes.py":
                continue
            code = "\n".join(
                line.split("#")[0] for line in f.read_text().splitlines()
            )
            if re.search(r"\bpl\.pallas_call\s*\(", code):
                pallas_modules.add(
                    str(f.relative_to(pkg_root.parent))
                    .removesuffix(".py").replace("/", ".")
                )
        assert pallas_modules == set(PROBED_MODULES), (
            "pallas_call callers and PROBED_MODULES disagree — a new kernel "
            "module must register bring-up probes in ops/probes.py: "
            f"{pallas_modules ^ set(PROBED_MODULES)}"
        )
        for mod, probes in PROBED_MODULES.items():
            for p in probes:
                assert p in KERNEL_PROBES, (mod, p)

    def test_probe_targets_resolve(self):
        for name in KERNEL_PROBES:
            fn = kernel_probe.resolve_target(name)
            assert callable(fn)

    def test_riskiest_kernels_run_last(self):
        # the in-place DMA scatters are the round-4 wedge-suspect class;
        # keep them at the end so a wedge doesn't block validating
        # everything else — bf16 first so a wedge there is attributed
        # before the (newer) int8 four-array variant even tries
        assert list(KERNEL_PROBES)[-2:] == ["scatter_kv", "scatter_kv_int8"]


class TestRealProbeViaSubprocess:
    def test_ragged_decode_probe_green(self):
        # full path: subprocess → jax import → interpret-mode kernel →
        # numerics vs reference → result file (CPU twin of chip bring-up)
        r = kernel_probe.run_probe("ragged_decode", timeout_s=240)
        assert r.ok, (r.status, r.error, r.log_tail)
        assert r.payload["max_err"] < 0.06

    @pytest.mark.slow
    def test_full_registry_green(self):
        results = kernel_probe.run_probes(timeout_s=240)
        bad = {k: (v.status, v.error) for k, v in results.items() if not v.ok}
        assert not bad, bad
