"""GRPO tests: mechanics (advantages, clipping) and learning — on a rigged
reward, the policy's probability of the rewarded token must increase."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestGRPOMechanics:
    def test_advantages_normalized(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.training.grpo import grpo_advantages

        adv = grpo_advantages(jnp.array([0.0, 0.0, 1.0, 1.0]))
        assert float(adv.mean()) == pytest.approx(0.0, abs=1e-6)
        assert float(adv[2]) > 0 > float(adv[0])

    @pytest.mark.slow
    def test_policy_learns_rewarded_token(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.training.grpo import GRPOConfig, GRPOTrainer

        cfg = llama.LlamaConfig(
            vocab_size=32, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=32, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jnp.array([1, 2, 3, 4], jnp.int32)
        LUCKY = 7  # reward completions whose first token is 7

        def reward_fn(tokens):
            return [float(int(t) == LUCKY) for t in np.asarray(tokens[:, 4])]

        def p_lucky(p):
            logits = llama.forward(p, prompt[None], cfg, attn_impl="xla")
            return float(jax.nn.softmax(logits[0, 3])[LUCKY])

        trainer = GRPOTrainer(
            cfg, params, reward_fn,
            GRPOConfig(group_size=16, max_new=2, temperature=1.0, kl_coef=0.0),
            learning_rate=5e-3,
        )
        before = p_lucky(trainer.policy)
        key = jax.random.PRNGKey(1)
        for _ in range(15):
            key, sub = jax.random.split(key)
            metrics = trainer.step(prompt, 4, sub)
        after = p_lucky(trainer.policy)
        assert after > before * 1.5, (before, after, metrics)

    def test_zero_advantage_no_update_direction(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.training.grpo import grpo_loss

        cfg = llama.LlamaConfig(
            vocab_size=32, dim=32, n_layers=1, n_heads=2, n_kv_heads=2,
            ffn_dim=64, max_seq_len=32, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32)
        lps = jnp.zeros((4, 4))
        loss, aux = grpo_loss(
            params, params, cfg, tokens, lps, jnp.zeros(4),
            prompt_len=4, max_new=4, clip_eps=0.2, kl_coef=0.1,
        )
        # zero advantages + identical ref: pg term 0, kl term 0
        assert float(aux["pg_loss"]) == pytest.approx(0.0, abs=1e-5)
