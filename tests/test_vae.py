"""VAE (AutoencoderKL) tests: shapes, roundtrip quality after a short
train, and the diffusers-name HF loader roundtrip (the zero-egress proof
that a real `vae/diffusion_pytorch_model.safetensors` drops in —
text_to_image.py:99-137's pipeline VAE)."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import vae

    cfg = vae.VAEConfig.tiny()
    params = vae.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestVAE:
    def test_encode_decode_shapes(self, jax, setup):
        from modal_examples_tpu.models import vae

        cfg, params = setup
        imgs = jax.random.uniform(jax.random.PRNGKey(1), (2, 32, 32, 3)) * 2 - 1
        z = vae.encode(params, imgs, cfg)
        assert z.shape == (2, 32 // cfg.downscale, 32 // cfg.downscale,
                           cfg.latent_channels)
        out = vae.decode(params, z, cfg)
        assert out.shape == imgs.shape
        assert float(jax.numpy.abs(out).max()) <= 1.0

    def test_posterior_sampling_differs_from_mean(self, jax, setup):
        from modal_examples_tpu.models import vae

        cfg, params = setup
        imgs = jax.random.uniform(jax.random.PRNGKey(2), (1, 32, 32, 3))
        mean = vae.encode(params, imgs, cfg)
        sampled = vae.encode(params, imgs, cfg, key=jax.random.PRNGKey(3))
        assert not np.allclose(np.asarray(mean), np.asarray(sampled))

    def test_reconstruction_improves_with_training(self, jax):
        """A few steps of plain reconstruction training must reduce MSE —
        the architecture is trainable end to end (conv gradients flow
        through groupnorm/attention/resize)."""
        import jax.numpy as jnp
        import optax

        from modal_examples_tpu.models import vae

        cfg = vae.VAEConfig(base=16, channel_mults=(1, 2), norm_groups=4)
        params = vae.init_params(jax.random.PRNGKey(0), cfg)
        imgs = jax.random.uniform(jax.random.PRNGKey(1), (4, 16, 16, 3)) * 2 - 1

        def loss_fn(p):
            z = vae.encode(p, imgs, cfg)
            out = vae.decode(p, z, cfg)
            return jnp.mean((out - imgs) ** 2)

        opt = optax.adam(1e-3)
        state = opt.init(params)
        first = None

        @jax.jit
        def step(params, state):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            upd, state = opt.update(grads, state)
            return optax.apply_updates(params, upd), state, loss

        for _ in range(12):
            params, state, loss = step(params, state)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_hf_weight_roundtrip(self, jax, tmp_path):
        """Export random params under diffusers AutoencoderKL names (torch
        conv/linear layouts), reload via load_hf_weights, require a
        bit-identical tree."""
        from safetensors.numpy import save_file

        from modal_examples_tpu.models import vae

        cfg = vae.VAEConfig.tiny()
        params = vae.init_params(jax.random.PRNGKey(0), cfg)
        raw = {}

        def put_conv(name, w, b):
            # HWIO -> torch OIHW
            raw[name + ".weight"] = np.ascontiguousarray(
                np.asarray(w).transpose(3, 2, 0, 1)
            )
            raw[name + ".bias"] = np.asarray(b)

        def put_resnet(prefix, p):
            raw[prefix + ".norm1.weight"] = np.asarray(p["norm1_scale"])
            raw[prefix + ".norm1.bias"] = np.asarray(p["norm1_bias"])
            put_conv(prefix + ".conv1", p["conv1"], p["conv1_b"])
            raw[prefix + ".norm2.weight"] = np.asarray(p["norm2_scale"])
            raw[prefix + ".norm2.bias"] = np.asarray(p["norm2_bias"])
            put_conv(prefix + ".conv2", p["conv2"], p["conv2_b"])
            if "shortcut" in p:
                put_conv(prefix + ".conv_shortcut", p["shortcut"], p["shortcut_b"])

        def put_attn(prefix, p):
            raw[prefix + ".group_norm.weight"] = np.asarray(p["norm_scale"])
            raw[prefix + ".group_norm.bias"] = np.asarray(p["norm_bias"])
            for ours, theirs in (
                ("q", "to_q"), ("k", "to_k"), ("v", "to_v"), ("o", "to_out.0")
            ):
                raw[f"{prefix}.{theirs}.weight"] = np.ascontiguousarray(
                    np.asarray(p[ours]).T
                )
                raw[f"{prefix}.{theirs}.bias"] = np.asarray(p[ours + "_b"])

        for side, tree in (("encoder", params["encoder"]),
                           ("decoder", params["decoder"])):
            put_conv(f"{side}.conv_in", tree["conv_in"], tree["conv_in_b"])
            put_resnet(f"{side}.mid_block.resnets.0", tree["mid_res1"])
            put_attn(f"{side}.mid_block.attentions.0", tree["mid_attn"])
            put_resnet(f"{side}.mid_block.resnets.1", tree["mid_res2"])
            raw[f"{side}.conv_norm_out.weight"] = np.asarray(tree["norm_out_scale"])
            raw[f"{side}.conv_norm_out.bias"] = np.asarray(tree["norm_out_bias"])
            put_conv(f"{side}.conv_out", tree["conv_out"], tree["conv_out_b"])
        for i, blk in enumerate(params["encoder"]["down"]):
            put_resnet(f"encoder.down_blocks.{i}.resnets.0", blk["res1"])
            put_resnet(f"encoder.down_blocks.{i}.resnets.1", blk["res2"])
            if "downsample" in blk:
                put_conv(
                    f"encoder.down_blocks.{i}.downsamplers.0.conv",
                    blk["downsample"], blk["downsample_b"],
                )
        for i, blk in enumerate(params["decoder"]["up"]):
            for j in range(3):
                put_resnet(f"decoder.up_blocks.{i}.resnets.{j}", blk[f"res{j+1}"])
            if "upsample" in blk:
                put_conv(
                    f"decoder.up_blocks.{i}.upsamplers.0.conv",
                    blk["upsample"], blk["upsample_b"],
                )

        save_file(raw, str(tmp_path / "diffusion_pytorch_model.safetensors"))
        loaded = vae.load_hf_weights(tmp_path, cfg, dtype=jax.numpy.float32)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params,
            loaded,
        )
