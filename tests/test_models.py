"""Model tests: llama forward/prefill/decode consistency (the serving path
must be numerically identical to the training path — the property that makes
the paged cache trustworthy)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def jnp(jax):
    import jax.numpy as jnp

    return jnp


@pytest.fixture(scope="module")
def tiny_f32(jax):
    from modal_examples_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=256, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=256, max_seq_len=256, dtype="float32",
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


class TestLlama:
    def test_forward_shapes_and_finite(self, jax, jnp, tiny_f32):
        from modal_examples_tpu.models import llama

        cfg, params = tiny_f32
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 256)
        logits = llama.forward(params, tokens, cfg)
        assert logits.shape == (2, 128, 256)
        assert bool(jnp.isfinite(logits).all())

    def test_attn_impls_agree(self, jax, jnp, tiny_f32):
        from modal_examples_tpu.models import llama

        cfg, params = tiny_f32
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 128), 0, 256)
        a = llama.forward(params, tokens, cfg, attn_impl="flash")
        b = llama.forward(params, tokens, cfg, attn_impl="xla")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    def test_paged_decode_matches_forward(self, jax, jnp, tiny_f32):
        from modal_examples_tpu.models import llama

        cfg, params = tiny_f32
        B, S = 2, 128
        tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, 256)
        logits_full = llama.forward(params, tokens, cfg)

        page_size, pages_per_seq = 16, 16
        n_pages = 1 + B * pages_per_seq
        shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
        k_pages = jnp.zeros(shape, jnp.float32)
        v_pages = jnp.zeros(shape, jnp.float32)
        pt = (1 + jnp.arange(B * pages_per_seq, dtype=jnp.int32)).reshape(B, -1)
        seq_lens = jnp.array([S - 1, S - 28])

        logits_pf, k_pages, v_pages = llama.prefill(
            params, tokens, k_pages, v_pages, pt, seq_lens, cfg
        )
        for b in range(B):
            np.testing.assert_allclose(
                np.asarray(logits_pf[b]),
                np.asarray(logits_full[b, int(seq_lens[b]) - 1]),
                atol=1e-3,
            )

        next_tok = jnp.array(
            [int(tokens[b, int(seq_lens[b])]) for b in range(B)], jnp.int32
        )
        logits_dec, _, _ = llama.decode_step(
            params, next_tok, seq_lens, k_pages, v_pages, pt,
            jnp.array([True, True]), cfg,
        )
        for b in range(B):
            np.testing.assert_allclose(
                np.asarray(logits_dec[b]),
                np.asarray(logits_full[b, int(seq_lens[b])]),
                atol=1e-3,
            )

    def test_hf_weight_roundtrip(self, jax, tmp_path):
        """Export random params under HF llama names, reload via
        load_hf_weights, require a bit-identical tree — proves the
        name/transpose mapping for the flagship loader."""
        import numpy as np
        from safetensors.numpy import save_file

        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig(
            vocab_size=64, dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
            ffn_dim=48, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        raw = {
            "model.embed_tokens.weight": np.asarray(params["embed"]),
            "model.norm.weight": np.asarray(params["final_norm"]),
            "lm_head.weight": np.ascontiguousarray(np.asarray(params["lm_head"]).T),
        }
        hf = {
            "wq": "self_attn.q_proj.weight", "wk": "self_attn.k_proj.weight",
            "wv": "self_attn.v_proj.weight", "wo": "self_attn.o_proj.weight",
            "gate": "mlp.gate_proj.weight", "up": "mlp.up_proj.weight",
            "down": "mlp.down_proj.weight",
        }
        norms = {
            "attn_norm": "input_layernorm.weight",
            "mlp_norm": "post_attention_layernorm.weight",
        }
        for i in range(cfg.n_layers):
            for ours, name in hf.items():
                raw[f"model.layers.{i}.{name}"] = np.ascontiguousarray(
                    np.asarray(params["layers"][ours][i]).T
                )
            for ours, name in norms.items():
                raw[f"model.layers.{i}.{name}"] = np.asarray(
                    params["layers"][ours][i]
                )
        save_file(raw, str(tmp_path / "model.safetensors"))
        loaded = llama.load_hf_weights(tmp_path, cfg, dtype=jax.numpy.float32)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            params,
            loaded,
        )

    def test_param_count_property(self):
        from modal_examples_tpu.models import llama

        cfg = llama.LlamaConfig.llama2_7b()
        assert 6.5e9 < cfg.param_count < 7.5e9

    def test_partition_specs_cover_tree(self, jax, tiny_f32):
        from modal_examples_tpu.models import llama

        cfg, params = tiny_f32
        specs = llama.partition_specs(cfg)
        # same tree structure: zip must succeed leaf-for-leaf
        import jax.tree_util as jtu
        from jax.sharding import PartitionSpec

        p_leaves = jtu.tree_structure(params)
        s_leaves = jtu.tree_structure(
            specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
        )
        assert p_leaves == s_leaves
