"""Whisper model + audio frontend tests."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class TestAudio:
    def test_log_mel_shape_and_range(self):
        from modal_examples_tpu.utils.audio import (
            log_mel_spectrogram, synth_tone_audio,
        )

        audio = synth_tone_audio([440.0], 1.0)
        mel = log_mel_spectrogram(audio, pad_to_chunk=False)
        assert mel.shape[1] == 80
        assert 95 <= mel.shape[0] <= 100  # ~1s at 10ms hop
        assert np.isfinite(mel).all()

    def test_distinct_tones_distinct_mels(self):
        from modal_examples_tpu.utils.audio import (
            log_mel_spectrogram, synth_tone_audio,
        )

        a = log_mel_spectrogram(synth_tone_audio([440.0]), pad_to_chunk=False)
        b = log_mel_spectrogram(synth_tone_audio([880.0]), pad_to_chunk=False)
        assert np.abs(a - b).max() > 0.1

    def test_chunk_padding(self):
        from modal_examples_tpu.utils.audio import (
            N_FRAMES, log_mel_spectrogram, synth_tone_audio,
        )

        mel = log_mel_spectrogram(synth_tone_audio([440.0], 1.0))
        assert abs(mel.shape[0] - N_FRAMES) <= 2  # framing edge


class TestMetrics:
    def test_wer(self):
        from modal_examples_tpu.utils.metrics import word_error_rate

        assert word_error_rate(["a b c"], ["a b c"]) == 0.0
        assert word_error_rate(["a b c"], ["a x c"]) == pytest.approx(1 / 3)
        assert word_error_rate(["a b"], [""]) == 1.0


class TestWhisperModel:
    def test_forward_shapes(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import whisper

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(0), cfg)
        mel = jax.random.normal(jax.random.PRNGKey(1), (2, 200, cfg.n_mels))
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, cfg.vocab_size)
        states = whisper.encode(params, mel, cfg)
        assert states.shape == (2, 100, cfg.dim)  # stride-2 conv halves T
        logits = whisper.decode(params, tokens, states, cfg)
        assert logits.shape == (2, 8, cfg.vocab_size)

    def test_greedy_transcribe_static_shape(self, jax):
        from modal_examples_tpu.models import whisper

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(0), cfg)
        mel = jax.random.normal(jax.random.PRNGKey(1), (2, 200, cfg.n_mels))
        out = whisper.greedy_transcribe(
            params, mel, cfg, bos_id=0, eos_id=1, max_tokens=8
        )
        assert out.shape == (2, 7)

    def test_hf_weight_roundtrip(self, jax, tmp_path):
        """Export our random params under HF names, load them back through
        load_hf_weights, and verify the tree is bit-identical — proves the
        name/transpose mapping."""
        import numpy as np
        from safetensors.numpy import save_file

        from modal_examples_tpu.models import whisper

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(0), cfg)

        raw: dict[str, np.ndarray] = {}
        raw["model.encoder.conv1.weight"] = np.ascontiguousarray(
            np.asarray(params["conv1_w"]).transpose(2, 1, 0)
        )
        raw["model.encoder.conv1.bias"] = np.asarray(params["conv1_b"])
        raw["model.encoder.conv2.weight"] = np.ascontiguousarray(
            np.asarray(params["conv2_w"]).transpose(2, 1, 0)
        )
        raw["model.encoder.conv2.bias"] = np.asarray(params["conv2_b"])
        raw["model.encoder.layer_norm.weight"] = np.asarray(params["enc_ln_w"])
        raw["model.encoder.layer_norm.bias"] = np.asarray(params["enc_ln_b"])
        raw["model.decoder.embed_tokens.weight"] = np.asarray(params["tok_emb"])
        raw["model.decoder.embed_positions.weight"] = np.asarray(params["pos_emb"])
        raw["model.decoder.layer_norm.weight"] = np.asarray(params["dec_ln_w"])
        raw["model.decoder.layer_norm.bias"] = np.asarray(params["dec_ln_b"])

        hf_names = {
            "ln1_w": ("self_attn_layer_norm.weight", False),
            "ln1_b": ("self_attn_layer_norm.bias", False),
            "wq": ("self_attn.q_proj.weight", True),
            "bq": ("self_attn.q_proj.bias", False),
            "wk": ("self_attn.k_proj.weight", True),
            "wv": ("self_attn.v_proj.weight", True),
            "bv": ("self_attn.v_proj.bias", False),
            "wo": ("self_attn.out_proj.weight", True),
            "bo": ("self_attn.out_proj.bias", False),
            "ln2_w": ("final_layer_norm.weight", False),
            "ln2_b": ("final_layer_norm.bias", False),
            "fc_w": ("fc1.weight", True),
            "fc_b": ("fc1.bias", False),
            "proj_w": ("fc2.weight", True),
            "proj_b": ("fc2.bias", False),
            "xln_w": ("encoder_attn_layer_norm.weight", False),
            "xln_b": ("encoder_attn_layer_norm.bias", False),
            "xwq": ("encoder_attn.q_proj.weight", True),
            "xbq": ("encoder_attn.q_proj.bias", False),
            "xwk": ("encoder_attn.k_proj.weight", True),
            "xwv": ("encoder_attn.v_proj.weight", True),
            "xbv": ("encoder_attn.v_proj.bias", False),
            "xwo": ("encoder_attn.out_proj.weight", True),
            "xbo": ("encoder_attn.out_proj.bias", False),
        }
        for side, L in (("encoder", cfg.n_audio_layers), ("decoder", cfg.n_text_layers)):
            tree = params["enc" if side == "encoder" else "dec"]
            for ours, (hf, transpose) in hf_names.items():
                if ours not in tree:
                    continue
                for i in range(L):
                    arr = np.asarray(tree[ours][i])
                    raw[f"model.{side}.layers.{i}.{hf}"] = np.ascontiguousarray(
                        arr.T if transpose else arr
                    )
        save_file(raw, str(tmp_path / "model.safetensors"))

        loaded = whisper.load_hf_weights(tmp_path, cfg)
        import jax as jx

        # tree_map checks STRUCTURE (missing/extra keys fail) and values
        jx.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            params,
            loaded,
        )

    def test_finetune_loss_decreases(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import whisper
        from modal_examples_tpu.training import (
            Trainer, cross_entropy_loss, make_optimizer,
        )

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(0), cfg)
        mel = jax.random.normal(jax.random.PRNGKey(1), (2, 200, cfg.n_mels))
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, cfg.vocab_size)

        def loss_fn(p, b):
            logits = whisper.forward(p, b["mel"], b["tokens"], cfg)
            return cross_entropy_loss(logits[:, :-1], b["tokens"][:, 1:])

        t = Trainer(loss_fn, make_optimizer(1e-3))
        state = t.init_state(params)
        first = None
        for _ in range(8):
            state, m = t.train_step(state, {"mel": mel, "tokens": tokens})
            first = first or float(m["loss"])
        assert float(m["loss"]) < first


class TestWordTimestamps:
    """Word-level timestamp alignment (the whisperx_transcribe.py
    capability) via Whisper's own cross-attention DTW. The ALGORITHM is
    proven on constructed attention; end-to-end quality tracks checkpoint
    quality (real weights load through the proven HF loader — the
    cross-impl tests pin the attention conventions)."""

    def test_decode_attn_flag_matches_plain_decode(self, jax):
        """decode(return_cross_attn=True) must produce the same logits as
        the plain path — one implementation, two outputs."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import whisper

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(0), cfg)
        mel = jax.random.normal(jax.random.PRNGKey(1), (2, 100, cfg.n_mels))
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 7), 0,
                                  cfg.vocab_size)
        states = whisper.encode(params, mel, cfg)
        want = whisper.decode(params, toks, states, cfg)
        got, attn = whisper.decode(
            params, toks, states, cfg, return_cross_attn=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)
        L, B, S, Ta = attn.shape  # head-mean: no H axis materialized
        assert (L, B, S) == (cfg.n_text_layers, 2, 7)
        # head-means of probability rows still sum to 1 over audio frames
        np.testing.assert_allclose(
            np.asarray(attn.sum(-1)), 1.0, atol=1e-4
        )

    def test_dtw_block_diagonal_alignment(self):
        """Attention concentrated on each token's true segment must yield
        that segment's frames — the algorithm-level quality proof."""
        from modal_examples_tpu.models.whisper import dtw_path

        S, T, seg = 4, 20, 5  # token k spans frames [5k, 5k+5)
        attn = np.full((S, T), 1e-6)
        for k in range(S):
            attn[k, k * seg : (k + 1) * seg] = 1.0
        ends = dtw_path(-np.log(attn / attn.sum(-1, keepdims=True)))
        assert list(ends) == [4, 9, 14, 19], list(ends)

    def test_dtw_shifted_and_uneven_segments(self):
        from modal_examples_tpu.models.whisper import dtw_path

        # token 0 -> frames 2..7, token 1 -> 8..9, token 2 -> 10..17
        attn = np.full((3, 18), 1e-6)
        attn[0, 2:8] = 1.0
        attn[1, 8:10] = 1.0
        attn[2, 10:18] = 1.0
        ends = dtw_path(-np.log(attn / attn.sum(-1, keepdims=True)))
        assert list(ends) == [7, 9, 17], list(ends)
        assert all(a <= b for a, b in zip(ends, ends[1:]))  # monotone

    def test_align_tokens_shape_monotone_bounded(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import whisper

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(3), cfg)
        Tmel = 120
        mel = jax.random.normal(jax.random.PRNGKey(4), (2, Tmel, cfg.n_mels))
        toks = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 2,
                                  cfg.vocab_size)
        times = whisper.align_tokens(params, mel, toks, cfg)
        assert times.shape == (2, 6, 2)
        dur = (Tmel // 2) * 0.02  # encoder downsamples 2x, 20ms frames
        for b in range(2):
            for s in range(6):
                start, end = times[b, s]
                assert 0.0 <= start <= end <= dur + 1e-6
            ends = times[b, :, 1]
            assert all(a <= b_ for a, b_ in zip(ends, ends[1:]))  # monotone

    def test_words_with_times_grouping(self):
        from modal_examples_tpu.models.whisper import words_with_times

        # "hi yo" in byte tokens with per-token times
        ids = [ord(c) for c in "hi yo"]
        times = [(0.0, 0.1), (0.1, 0.2), (0.2, 0.3), (0.3, 0.4), (0.4, 0.5)]
        words = words_with_times(
            ids, times, lambda t: bytes(t).decode(), space_ids=(32,)
        )
        assert [w["word"] for w in words] == ["hi", "yo"]
        assert words[0]["start"] == 0.0 and words[0]["end"] == 0.2
        assert words[1]["start"] == 0.3 and words[1]["end"] == 0.5

    def test_words_with_times_stops_at_eos(self):
        """greedy_transcribe output is eos-padded; the padding must not
        glue onto the last word or stretch its end time."""
        from modal_examples_tpu.models.whisper import words_with_times

        ids = [ord("h"), ord("i"), 1, 1, 1]  # "hi" + eos padding (id 1)
        times = [(0.0, 0.1), (0.1, 0.2), (0.2, 0.3), (0.3, 0.4), (0.4, 0.5)]
        words = words_with_times(
            ids, times, lambda t: bytes(t).decode(), space_ids=(32,),
            eos_ids=(1,),
        )
        assert words == [{"word": "hi", "start": 0.0, "end": 0.2}]

    def test_align_tokens_composes_with_greedy_transcribe(self, jax):
        """greedy_transcribe strips BOS; bos_id= makes the two compose
        with rows matching the stripped sequence."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import whisper

        cfg = whisper.WhisperConfig.test_tiny()
        params = whisper.init_params(jax.random.PRNGKey(6), cfg)
        mel = jax.random.normal(jax.random.PRNGKey(7), (1, 100, cfg.n_mels))
        out = whisper.greedy_transcribe(
            params, mel, cfg, bos_id=0, eos_id=1, max_tokens=6
        )
        assert out.shape == (1, 5)  # bos stripped
        times = whisper.align_tokens(params, mel, out, cfg, bos_id=0)
        assert times.shape == (1, 5, 2)  # one row per OUTPUT token
        # adjacent spans touch (openai/whisper boundary convention)
        for s in range(4):
            assert abs(times[0, s, 1] - times[0, s + 1, 0]) < 1e-6
