"""Disaggregated prefill/decode serving (serving/disagg, docs/disagg.md):
wire codec roundtrips + corruption detection, chunked transfer with
resumable retry, role-aware routing, the end-to-end token-identity
acceptance (prefill on A + migrate + decode on B == unified, bf16 AND int8,
including a host-tier prefix hit), mid-transfer death -> unified fallback,
and abort/deadline during an in-flight migration releasing reservations and
pages on BOTH replicas."""

import numpy as np
import pytest

from modal_examples_tpu.serving.disagg.transport import (
    ChunkAssembler,
    LoopbackChannel,
    TransferAborted,
    TransportError,
    chain_hashes,
    deserialize_block,
    iter_chunks,
    serialize_block,
    transfer,
)


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _cache(jax, kv_dtype, n_pages=6):
    from modal_examples_tpu.serving.kv_cache import PagedKVCache

    return PagedKVCache.create(
        n_layers=2, n_kv_heads=2, head_dim=4, n_pages=n_pages, page_size=4,
        kv_dtype=kv_dtype, prefer_native=False,
    )


def _fill_cache(jax, cache, seed=0):
    """Write distinguishable values into every page of every leaf."""
    import jax.numpy as jnp

    from modal_examples_tpu.serving.disagg.transport import wire_leaves

    rng = np.random.default_rng(seed)
    flat, treedef = jax.tree_util.tree_flatten(cache)
    new = []
    for leaf in flat:
        vals = rng.normal(size=leaf.shape).astype(np.float32)
        new.append(jnp.asarray(vals).astype(leaf.dtype))
    rebuilt = jax.tree_util.tree_unflatten(treedef, new)
    cache.k_pages, cache.v_pages = rebuilt.k_pages, rebuilt.v_pages
    assert len(wire_leaves(cache)) == len(flat)


class TestTransport:
    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
    def test_extract_serialize_adopt_roundtrip_is_exact(self, jax, kv_dtype):
        """Every cache leaf survives the wire bit-exactly: extract ->
        serialize -> deserialize -> adopt into a second cache reproduces
        the source pages (the property token-identity rests on)."""
        from modal_examples_tpu.serving.disagg.transport import (
            adopt_pages,
            extract_pages,
            wire_leaves,
        )

        src = _cache(jax, kv_dtype)
        _fill_cache(jax, src, seed=1)
        page_ids = [2, 4, 1]  # arbitrary order: table order must be kept
        block = extract_pages(src, page_ids, meta={"position": 9})
        wire = serialize_block(block)
        back = deserialize_block(wire)
        assert back.kv_dtype == kv_dtype
        assert back.meta["position"] == 9
        dst = _cache(jax, kv_dtype)
        dst_ids = [3, 1, 5]
        adopt_pages(dst, back, dst_ids)
        for (name, s_leaf), (_, d_leaf) in zip(
            wire_leaves(src), wire_leaves(dst)
        ):
            s = np.asarray(s_leaf[:, np.asarray(page_ids)])
            d = np.asarray(d_leaf[:, np.asarray(dst_ids)])
            assert np.array_equal(s, d), name

    def test_int8_ships_scale_rows_and_half_the_bytes(self, jax):
        from modal_examples_tpu.serving.kv_cache import PagedKVCache
        from modal_examples_tpu.serving.disagg.transport import extract_pages

        def big(kv_dtype):  # realistic head_dim so scale overhead is ~6%
            c = PagedKVCache.create(
                n_layers=2, n_kv_heads=2, head_dim=64, n_pages=6,
                page_size=4, kv_dtype=kv_dtype, prefer_native=False,
            )
            _fill_cache(jax, c, seed=2)
            return c

        wire_bf = serialize_block(extract_pages(big("bfloat16"), [1, 2]))
        wire_q = serialize_block(extract_pages(big("int8"), [1, 2]))
        block_q = deserialize_block(wire_q)
        assert {n for n in block_q.leaves if n.endswith(".scale")}, (
            "int8 blocks must carry the f32 scale rows"
        )
        # int8 data halves the bf16 payload; f32 scales add ~1/D
        assert len(wire_q) < 0.65 * len(wire_bf)

    def test_corrupt_payload_is_a_loud_error(self, jax):
        from modal_examples_tpu.serving.disagg.transport import extract_pages

        src = _cache(jax, "int8")
        _fill_cache(jax, src, seed=3)
        wire = bytearray(serialize_block(extract_pages(src, [1])))
        wire[-3] ^= 0xFF  # flip a byte in the last leaf's payload
        with pytest.raises(TransportError, match="crc"):
            deserialize_block(bytes(wire))

    def test_dtype_and_geometry_mismatches_rejected(self, jax):
        from modal_examples_tpu.serving.disagg.transport import (
            adopt_pages,
            extract_pages,
        )

        src = _cache(jax, "int8")
        block = extract_pages(src, [1])
        with pytest.raises(TransportError, match="kv_dtype"):
            adopt_pages(_cache(jax, "bfloat16"), block, [1])
        with pytest.raises(TransportError, match="pages"):
            adopt_pages(_cache(jax, "int8"), block, [1, 2])

    def test_chain_hashes_are_position_dependent(self):
        a = chain_hashes([1, 2, 3, 4, 1, 2, 3, 4], page_size=4)
        assert len(a) == 2
        assert a[0] != a[1]  # same tokens, different depth -> different hash
        b = chain_hashes([9, 9, 9, 9, 1, 2, 3, 4], page_size=4)
        assert a[1] != b[1]  # the chain encodes the whole prefix


class TestChunkedTransfer:
    def test_chunks_reassemble(self):
        payload = bytes(range(256)) * 40
        chunks = iter_chunks(payload, "t1", chunk_bytes=1000)
        asm = ChunkAssembler("t1")
        for c in reversed(chunks):  # arrival order must not matter
            assert asm.add(c)
        assert asm.complete and asm.payload() == payload

    def test_missing_and_corrupt_chunks_are_tracked(self):
        payload = b"x" * 5000
        chunks = iter_chunks(payload, "t2", chunk_bytes=1000)
        asm = ChunkAssembler("t2")
        kind, tid, seq, total, crc, piece = chunks[2]
        asm.add((kind, tid, seq, total, crc, b"!" + piece[1:]))  # corrupt
        for c in chunks[:2] + chunks[3:]:
            asm.add(c)
        assert not asm.complete
        assert asm.missing() == [2] and asm.corrupt == 1
        asm.add(chunks[2])  # resumable retry: just the gap
        assert asm.complete and asm.payload() == payload

    def test_transfer_retries_only_the_gaps(self):
        """A channel that corrupts two chunks on the first pass: the second
        round re-sends exactly those and the transfer completes."""

        class Flaky(LoopbackChannel):
            def __init__(self):
                super().__init__()
                self.sent = []
                self._dropped = set()

            def send(self, chunk):
                self.sent.append(chunk[2])
                if chunk[2] in (1, 3) and chunk[2] not in self._dropped:
                    self._dropped.add(chunk[2])
                    mangled = chunk[:4] + (chunk[4], b"\x00" * len(chunk[5]))
                    super().send(mangled)
                    return
                super().send(chunk)

        ch = Flaky()
        payload = bytes(range(256)) * 30
        out = transfer(payload, ch, transfer_id="t3", chunk_bytes=1024)
        assert out == payload
        # second round resent ONLY the two corrupt sequence numbers
        n_chunks = len(iter_chunks(payload, "t3", 1024))
        assert ch.sent == list(range(n_chunks)) + [1, 3]

    def test_transfer_gives_up_loudly(self):
        class Dead(LoopbackChannel):
            def send(self, chunk):
                pass  # every chunk vanishes

        with pytest.raises(TransportError, match="missing"):
            transfer(b"abc" * 100, Dead(), transfer_id="t4", chunk_bytes=64,
                     max_rounds=2)

    def test_transfer_abort_checks_between_chunks(self):
        sent = []

        class Counting(LoopbackChannel):
            def send(self, chunk):
                sent.append(chunk)
                super().send(chunk)

        with pytest.raises(TransferAborted):
            transfer(
                b"z" * 4096,
                Counting(),
                transfer_id="t5",
                chunk_bytes=256,
                should_abort=lambda: len(sent) >= 3,
            )
        assert len(sent) == 3  # stopped mid-stream, not after the tail


def _tiny_engine(jax, seed=0, **kw):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    kw.setdefault("max_slots", 2)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_buckets", (32,))
    return LLMEngine(llama.LlamaConfig.tiny(), seed=seed, **kw)


def _pair(jax, kv_dtype=None, seed=0, coord_kw=None, prefill_kw=None,
          decode_kw=None):
    from modal_examples_tpu.scheduling import EngineReplica
    from modal_examples_tpu.serving.disagg import DisaggCoordinator

    kw = {"kv_dtype": kv_dtype} if kv_dtype else {}
    ep = _tiny_engine(jax, seed=seed, **kw, **(prefill_kw or {}))
    ed = _tiny_engine(jax, seed=seed, **kw, **(decode_kw or {}))
    co = DisaggCoordinator(
        [
            EngineReplica(ep, "pre-0", role="prefill"),
            EngineReplica(ed, "dec-0", role="decode"),
        ],
        **{"chunk_bytes": 512, **(coord_kw or {})},
    )
    return ep, ed, co


def _drain_used(engine) -> int:
    """Pages still allocated after draining the zero-ref prefix cache —
    the leak detector: 0 means nothing is orphaned."""
    if engine.prefix_cache is not None:
        engine.prefix_cache.evict(10_000)
    return (engine.cache.n_pages - 1) - engine.cache.allocator.available


PROMPT = "the quick brown fox jumps over the lazy dog and then some more"


class TestDisaggE2E:
    @pytest.mark.parametrize("kv_dtype", [None, "int8"],
                             ids=["bf16", "int8"])
    @pytest.mark.parametrize("temperature", [0.0, 1.0],
                             ids=["greedy", "seeded"])
    def test_token_identical_to_unified(self, jax, kv_dtype, temperature):
        """Acceptance: a request prefilled on replica A and decoded on
        replica B produces token-identical output to the same request on a
        unified replica, bf16 and int8, greedy and seeded sampling."""
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=12, temperature=temperature,
                                seed=None if temperature == 0.0 else 123)
        kw = {"kv_dtype": kv_dtype} if kv_dtype else {}
        uni = _tiny_engine(jax, seed=0, **kw)
        try:
            ref = uni.generate(PROMPT, params)
        finally:
            uni.stop()
        assert ref  # the reference must actually produce text
        ep, ed, co = _pair(jax, kv_dtype, seed=0)
        try:
            req = co.submit(PROMPT, params)
            out = "".join(co.stream(req))
            assert out == ref
            assert req.finish_reason in ("stop", "length")
            assert co.migrations_ok == 1 and co.migrations_fallback == 0
            # no leaked pages or reservations on either replica
            assert ed.admission.reserved_pages == 0
            assert _drain_used(ep) == 0
            assert _drain_used(ed) == 0
        finally:
            ed.stop()

    def test_host_tier_prefix_hit_still_token_identical(self, jax):
        """Acceptance (tiered): the shared prefix is evicted from the
        prefill replica's HBM trie into the host-RAM tier, and the next
        disagg request promotes it back — tier hit recorded, output still
        token-identical to unified."""
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=10, temperature=0.0)
        uni = _tiny_engine(jax, seed=0, kv_dtype="int8")
        try:
            ref = uni.generate(PROMPT, params)
        finally:
            uni.stop()
        ep, ed, co = _pair(
            jax, "int8", seed=0,
            prefill_kw={"tiered_prefix": {"host_bytes": 1 << 20}},
        )
        try:
            first = co.submit(PROMPT, params)
            assert "".join(co.stream(first)) == ref
            # evict the trie: pages spill to the host tier
            ep.prefix_cache.evict(10_000)
            assert ep.tiered.stats()["host"]["blocks"] > 0
            again = co.submit(PROMPT, params)
            assert "".join(co.stream(again)) == ref
            assert ep.tiered.stats()["hits"]["host"] > 0
        finally:
            ed.stop()

    def test_mid_transfer_death_falls_back_to_unified(self, jax):
        """Acceptance: the channel dies mid-stream (replica death) — the
        coordinator re-prefills on the decode-capable replica, output still
        matches unified, and the router keeps serving afterwards."""
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=10, temperature=0.0)
        uni = _tiny_engine(jax, seed=0)
        try:
            ref = uni.generate(PROMPT, params)
        finally:
            uni.stop()

        class DiesMidStream(LoopbackChannel):
            def __init__(self):
                super().__init__()
                self.n = 0

            def send(self, chunk):
                self.n += 1
                if self.n == 2:
                    raise ConnectionError("prefill replica died")
                super().send(chunk)

        ep, ed, co = _pair(
            jax, seed=0, coord_kw={"channel_factory": DiesMidStream}
        )
        try:
            req = co.submit(PROMPT, params)
            out = "".join(co.stream(req))
            assert out == ref
            assert co.migrations_fallback == 1
            assert ed.admission.reserved_pages == 0
            # router is not wedged: the next request also completes (its
            # migration dies too; fallback keeps serving)
            req2 = co.submit(PROMPT, params)
            assert "".join(co.stream(req2)) == ref
            assert _drain_used(ed) == 0
        finally:
            ed.stop()

    def test_no_prefill_peer_serves_unified(self, jax):
        """Fallback by plan: a fleet with no prefill replicas routes
        straight to unified serving, no migration attempted."""
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.serving.disagg import DisaggCoordinator

        ed = _tiny_engine(jax, seed=0)
        co = DisaggCoordinator([EngineReplica(ed, "solo", role="unified")])
        try:
            req = co.submit(PROMPT, SamplingParams(max_tokens=4))
            "".join(co.stream(req))
            assert req.finish_reason in ("stop", "length")
            assert co.migrations_ok == 0
        finally:
            ed.stop()


class TestAbortDuringMigration:
    """The PR 4 abort-of-queued regression, extended to the migration
    window: a client abort or deadline expiry while pages are ON THE WIRE
    must release the decode-side reservation and leave no orphaned pages on
    either replica."""

    def _gated_pair(self, jax, clock=None):
        """Coordinator whose channel fires a callback after the first
        chunk — the deterministic 'mid-transfer' hook."""
        hook = {"fn": None}

        class Gated(LoopbackChannel):
            def __init__(self):
                super().__init__()
                self.n = 0

            def send(self, chunk):
                self.n += 1
                if self.n == 1 and hook["fn"] is not None:
                    hook["fn"]()
                super().send(chunk)

        decode_kw = {"clock": clock} if clock is not None else {}
        ep, ed, co = _pair(
            jax, seed=0,
            coord_kw={"channel_factory": Gated, "chunk_bytes": 64},
            decode_kw=decode_kw,
        )
        return ep, ed, co, hook

    def test_client_abort_mid_transfer_releases_both_sides(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        ep, ed, co, hook = self._gated_pair(jax)
        try:
            hook["fn"] = lambda: co.migrations()[0].request.__setattr__(
                "aborted", True
            )
            req = co.submit(PROMPT, SamplingParams(max_tokens=16))
            assert "".join(co.stream(req)) == ""  # nothing decoded
            assert req.finish_reason == "stop"
            assert co.migrations_aborted == 1
            assert ed.admission.reserved_pages == 0
            assert _drain_used(ep) == 0, "orphaned pages on the prefill side"
            assert _drain_used(ed) == 0, "orphaned pages on the decode side"
            assert co.migrations() == []
        finally:
            ed.stop()

    def test_deadline_expiry_mid_transfer_is_a_deadline_miss(self, jax):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        clock = FakeClock()
        ep, ed, co, hook = self._gated_pair(jax, clock=clock)
        try:
            hook["fn"] = lambda: clock.advance(10.0)  # blow the deadline
            misses_before = default_registry.value(
                C.DEADLINE_MISSES_TOTAL, {"stage": "migrating"}
            )
            req = co.submit(
                PROMPT, SamplingParams(max_tokens=16, deadline_s=1.0)
            )
            assert "".join(co.stream(req)) == ""
            assert req.finish_reason == "deadline"
            assert default_registry.value(
                C.DEADLINE_MISSES_TOTAL, {"stage": "migrating"}
            ) == misses_before + 1
            assert ed.admission.reserved_pages == 0
            assert _drain_used(ep) == 0
            assert _drain_used(ed) == 0
        finally:
            ed.stop()

    def test_abort_of_adopted_queued_request_releases_reservation(self, jax):
        """After a successful migration the request queues on the decode
        policy like any other — abort-of-queued must release its
        reservation AND drop the adopted block without a slot ever being
        claimed (the decode engine never runs here)."""
        from modal_examples_tpu.serving import SamplingParams

        ep, ed, co = _pair(jax, seed=0)
        try:
            req = co.submit(PROMPT, SamplingParams(max_tokens=8))
            # migration done, request queued on the (never-started) decode
            # engine; abort before any scheduler tick
            assert ed.policy.total_depth() == 1
            co.abort(req)
            assert ed.policy.total_depth() == 0
            assert ed.admission.reserved_pages == 0
            assert req.out_queue.get(timeout=1).reason == "stop"
            assert _drain_used(ep) == 0
            assert _drain_used(ed) == 0
        finally:
            ed.stop()


class TestRolesAndRouting:
    def test_route_never_places_on_prefill_replicas(self, jax):
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )

        ep = _tiny_engine(jax, seed=0)
        ed = _tiny_engine(jax, seed=0)
        router = PrefixAffinityRouter(
            [
                EngineReplica(ep, "pre", role="prefill"),
                EngineReplica(ed, "dec", role="decode"),
            ]
        )
        for prompt in ("alpha", "beta", "gamma", PROMPT):
            assert router.route(prompt).name == "dec"
        pre, dec = router.plan(PROMPT)
        assert pre.name == "pre" and dec.name == "dec"

    def test_plan_with_no_prefillers_returns_none(self, jax):
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )

        ed = _tiny_engine(jax, seed=0)
        router = PrefixAffinityRouter([EngineReplica(ed, "u")])
        pre, dec = router.plan(PROMPT)
        assert pre is None and dec.name == "u"

    def test_prefill_only_fleet_is_rejected(self, jax):
        from modal_examples_tpu.scheduling import (
            EngineReplica,
            PrefixAffinityRouter,
        )

        ep = _tiny_engine(jax, seed=0)
        with pytest.raises(ValueError, match="decode-capable"):
            PrefixAffinityRouter([EngineReplica(ep, "p", role="prefill")])

    def test_bad_role_rejected(self, jax):
        from modal_examples_tpu.scheduling import EngineReplica

        with pytest.raises(ValueError, match="role"):
            EngineReplica(_tiny_engine(jax, seed=0), "x", role="turbo")

    def test_coordinator_rejects_mixed_cache_geometry(self, jax):
        from modal_examples_tpu.scheduling import EngineReplica
        from modal_examples_tpu.serving.disagg import DisaggCoordinator

        a = _tiny_engine(jax, seed=0)
        b = _tiny_engine(jax, seed=0, kv_dtype="int8")
        with pytest.raises(ValueError, match="kv_dtype"):
            DisaggCoordinator(
                [
                    EngineReplica(a, "a", role="prefill"),
                    EngineReplica(b, "b", role="decode"),
                ]
            )

    def test_serving_engines_excludes_prefill(self, jax):
        ep, ed, co = _pair(jax, seed=0)
        assert co.serving_engines() == [ed]
        ed.stop()

    def test_prefill_sync_refuses_running_engine(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        eng = _tiny_engine(jax, seed=0)
        eng.start()
        try:
            req = eng.make_request("hello", SamplingParams(max_tokens=2))
            with pytest.raises(RuntimeError, match="scheduler loop"):
                eng.prefill_sync(req)
        finally:
            eng.stop()

    def test_replica_role_metric_emitted(self, jax):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.utils.prometheus import default_registry

        ep, ed, co = _pair(jax, seed=0)
        assert default_registry.value(
            C.REPLICA_ROLE, {"replica": "pre-0", "role": "prefill"}
        ) == 1.0
        assert default_registry.value(
            C.REPLICA_ROLE, {"replica": "dec-0", "role": "decode"}
        ) == 1.0
        ed.stop()


class TestTieredCache:
    def test_spill_promote_and_volume_churn_survival(self, jax):
        """Evicted prefix pages spill host-ward; a tiny host budget demotes
        them to the Volume; a FRESH engine over the same Volume promotes
        yesterday's prefix — warm prefixes survive replica churn."""
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.storage.volume import Volume

        params = SamplingParams(max_tokens=4, temperature=0.0)
        with Volume.ephemeral() as vol:
            tiered = {"host_bytes": 2048, "volume": vol}
            e1 = _tiny_engine(jax, seed=0, kv_dtype="int8",
                              tiered_prefix=tiered)
            try:
                ref = e1.generate(PROMPT, params)
            finally:
                e1.stop()
            e1.prefix_cache.evict(10_000)
            st = e1.tiered.stats()
            assert st["spilled"] > 0
            assert st["volume"]["blocks"] > 0, (
                "tiny host budget must demote blocks to the volume tier"
            )
            # push the remaining host-resident blocks down too, so the
            # fresh replica's CONSECUTIVE promote walk starts at page 0
            for h, data in list(e1.tiered._host.items()):
                e1.tiered._demote_to_volume(h, data)
            # replica churn: a brand-new engine finds the volume blocks
            e2 = _tiny_engine(jax, seed=0, kv_dtype="int8",
                              tiered_prefix=tiered)
            try:
                out = e2.generate(PROMPT, params)
            finally:
                e2.stop()
            assert out == ref
            assert e2.tiered.stats()["hits"]["volume"] > 0

    def test_corrupt_tier_block_is_dropped_not_adopted(self, jax):
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=4, temperature=0.0)
        e = _tiny_engine(jax, seed=0, tiered_prefix={"host_bytes": 1 << 20})
        try:
            ref = e.generate(PROMPT, params)
            e.prefix_cache.evict(10_000)
            # corrupt every spilled block in place
            for h in list(e.tiered._host):
                e.tiered._host[h] = e.tiered._host[h][:-4] + b"\x00123"
            out = e.generate(PROMPT, params)  # promote fails -> recompute
            assert out == ref
            assert e.tiered.stats()["hits"]["host"] == 0
        finally:
            e.stop()

    def test_tier_gauges_emitted(self, jax):
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        e = _tiny_engine(jax, seed=0, tiered_prefix={"host_bytes": 1 << 20})
        try:
            e.generate(PROMPT, SamplingParams(max_tokens=2))
            e.prefix_cache.evict(10_000)
            assert default_registry.value(
                C.PREFIX_TIER_PAGES, {"tier": "host"}
            ) > 0
        finally:
            e.stop()


class TestRequestTracing:
    """ISSUE 9 acceptance: one disagg request = ONE distributed trace id
    whose merged span tree covers queue, placement, prefill, per-chunk
    transfer, adoption, and decode with correct parentage — across
    per-replica TraceStores — plus span closure under failure (mid-
    transfer death, abort-during-migration: no dangling spans)."""

    def _traced_pair(self, jax, tmp_path, coord_kw=None, prefill_kw=None):
        from modal_examples_tpu.observability.trace import TraceStore

        stores = {
            "pre": TraceStore(root=tmp_path / "pre"),
            "dec": TraceStore(root=tmp_path / "dec"),
            "gw": TraceStore(root=tmp_path / "gw"),
        }
        ep, ed, co = _pair(
            jax, "int8", seed=0,
            prefill_kw={"trace_store": stores["pre"], **(prefill_kw or {})},
            decode_kw={"trace_store": stores["dec"]},
            coord_kw={"trace_store": stores["gw"], **(coord_kw or {})},
        )
        return ep, ed, co, list(stores.values())

    def test_disagg_request_yields_one_stitched_trace(self, jax, tmp_path):
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.observability.export import (
            spans_to_chrome_trace,
        )
        from modal_examples_tpu.serving import SamplingParams

        params = SamplingParams(max_tokens=6, temperature=0.0)
        ep, ed, co, stores = self._traced_pair(
            jax, tmp_path,
            prefill_kw={"tiered_prefix": {"host_bytes": 1 << 20}},
        )
        try:
            seed_req = co.submit(PROMPT, params)  # warms the prefix trie
            "".join(co.stream(seed_req))
            # spill the prefill replica's trie so the NEXT request's claim
            # promotes from the host tier — the acceptance's tiered hit
            ep.prefix_cache.evict(10_000)
            assert ep.tiered.stats()["host"]["blocks"] > 0
            req = co.submit(PROMPT, params)
            "".join(co.stream(req))
            assert req.finish_reason in ("stop", "length")
            assert req.trace is not None and req.trace.open_spans() == []
        finally:
            ed.stop()

        spans = rt.read_trace(req.request_id, stores=stores)
        assert spans and {s["trace_id"] for s in spans} == {req.request_id}
        by = {}
        for s in spans:
            by.setdefault(s["name"], []).append(s)
        assert {
            "request", "queue", "placement", "prefill", "migrate",
            "transfer", "chunk", "adopt", "decode", "tier_promote",
        } <= set(by), sorted(by)
        # every recorded span is CLOSED
        assert all(s["end"] is not None for s in spans)
        # parentage: queue/placement/migrate/decode under the root;
        # prefill + transfer + adopt under the migrate span; every chunk
        # under the transfer span
        root = by["request"][0]
        mig = by["migrate"][0]
        tr = by["transfer"][0]
        assert root["parent_id"] is None
        for name in ("queue", "placement", "migrate", "decode"):
            assert by[name][0]["parent_id"] == root["span_id"], name
        for name in ("prefill", "transfer", "adopt"):
            assert by[name][0]["parent_id"] == mig["span_id"], name
        assert all(c["parent_id"] == tr["span_id"] for c in by["chunk"])
        assert len(by["chunk"]) == -(-mig["attrs"]["wire_bytes"] // 512)
        # replica attribution: the spans landed in DIFFERENT stores yet
        # stitch — prefill on rep A, adopt/decode on rep B
        assert by["prefill"][0]["attrs"]["replica"] == "pre-0"
        assert by["adopt"][0]["attrs"]["replica"] == "dec-0"
        assert by["decode"][0]["attrs"]["replica"] == "dec-0"
        assert by["tier_promote"][0]["attrs"]["tier"] == "host"
        # the queue span's wait_s is ITS OWN residency, not the whole
        # migration (which is the migrate span's story)
        q = by["queue"][0]
        assert q["attrs"]["wait_s"] == pytest.approx(
            q["end"] - q["start"], abs=0.05
        )
        assert mig["attrs"]["result"] == "ok"
        assert mig["attrs"]["pages"] > 0
        assert root["attrs"]["finish_reason"] == req.finish_reason
        assert root["attrs"]["ttft_s"] > 0

        # `tpurun explain` renders the narrative from the merged stores
        lines = rt.explain_lines(spans, req.request_id)
        text = "\n".join(lines)
        assert "prefill on pre-0" in text
        assert "migrated" in text and "pre-0 -> dec-0" in text
        assert "decode on dec-0" in text and "TTFT" in text

        # the Perfetto export passes the existing schema check, with one
        # track per replica and the migration flow link
        doc = spans_to_chrome_trace(spans, req.request_id)
        assert doc["traceEvents"] and doc["displayTimeUnit"] in ("ms", "ns")
        for ev in doc["traceEvents"]:
            assert {"ph", "pid", "tid", "name"} <= set(ev), ev
            assert ev["ph"] in ("X", "i", "M", "s", "f"), ev
            if ev["ph"] == "X":
                assert ev["dur"] > 0 and ev["ts"] >= 0
        tracks = {
            ev["args"]["name"]
            for ev in doc["traceEvents"]
            if ev["ph"] == "M" and ev["name"] == "thread_name"
        }
        assert {"gateway", "pre-0", "dec-0"} <= tracks
        assert any(ev["ph"] == "s" for ev in doc["traceEvents"])

    def test_mid_transfer_death_closes_all_spans(self, jax, tmp_path):
        """Failure propagation: the channel dies mid-stream — unified
        fallback serves the request, and the trace closes every span
        (migrate/transfer marked error, no dangling chunk span)."""
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.serving import SamplingParams

        class DiesMidStream(LoopbackChannel):
            def __init__(self):
                super().__init__()
                self.n = 0

            def send(self, chunk):
                self.n += 1
                if self.n == 2:
                    raise ConnectionError("prefill replica died")
                super().send(chunk)

        ep, ed, co, stores = self._traced_pair(
            jax, tmp_path, coord_kw={"channel_factory": DiesMidStream}
        )
        try:
            req = co.submit(PROMPT, SamplingParams(max_tokens=6,
                                                   temperature=0.0))
            out = "".join(co.stream(req))
            assert out and req.finish_reason in ("stop", "length")
            assert co.migrations_fallback == 1
            assert req.trace is not None and req.trace.open_spans() == []
        finally:
            ed.stop()
        spans = rt.read_trace(req.request_id, stores=stores)
        assert all(s["end"] is not None for s in spans)
        by = {s["name"]: s for s in spans}
        assert by["migrate"]["attrs"]["result"] == "fallback"
        assert by["migrate"]["status"] == "error"
        assert by["transfer"]["status"] == "error"
        # the fallback re-prefill recorded on the DECODE replica, at root
        prefills = [s for s in spans if s["name"] == "prefill"]
        fallback = [p for p in prefills if p["attrs"]["replica"] == "dec-0"]
        assert fallback and fallback[0]["parent_id"] == by["request"]["span_id"]
        assert by["request"]["attrs"]["finish_reason"] == req.finish_reason

    def test_abort_mid_migration_closes_all_spans(self, jax, tmp_path):
        from modal_examples_tpu.observability import reqtrace as rt
        from modal_examples_tpu.serving import SamplingParams

        hook = {"fn": None}

        class Gated(LoopbackChannel):
            def __init__(self):
                super().__init__()
                self.n = 0

            def send(self, chunk):
                self.n += 1
                if self.n == 1 and hook["fn"] is not None:
                    hook["fn"]()
                super().send(chunk)

        ep, ed, co, stores = self._traced_pair(
            jax, tmp_path,
            coord_kw={"channel_factory": Gated, "chunk_bytes": 64},
        )
        try:
            hook["fn"] = lambda: co.migrations()[0].request.__setattr__(
                "aborted", True
            )
            req = co.submit(PROMPT, SamplingParams(max_tokens=16))
            assert "".join(co.stream(req)) == ""
            assert req.finish_reason == "stop"
            assert req.trace is not None and req.trace.open_spans() == []
        finally:
            ed.stop()
        spans = rt.read_trace(req.request_id, stores=stores)
        assert all(s["end"] is not None for s in spans)
        by = {s["name"]: s for s in spans}
        assert by["migrate"]["attrs"]["result"] == "aborted"
        assert by["request"]["attrs"]["finish_reason"] == "stop"
        assert "decode" not in by  # nothing ever decoded

    def test_wire_context_rides_the_mtkv1_envelope(self, jax):
        """The block meta carries {trace_id, parent_id} — what a
        cross-process decode replica reconstructs the context from."""
        from modal_examples_tpu.serving import SamplingParams

        eng = _tiny_engine(jax, seed=0)
        req = eng.make_request("hello wire", SamplingParams(max_tokens=2))
        req._trace_parent = "sp-migrate-x"
        state = eng.prefill_sync(req)
        block = eng.extract_request_pages(req, state)
        eng.release_claim(state["claim"])
        assert block.meta["trace"] == {
            "trace_id": req.request_id, "parent_id": "sp-migrate-x",
        }


class TestGatewaySnapshot:
    def test_disagg_snapshot_shape(self, jax):
        """The gateway /disagg payload renders from the live registry."""
        from modal_examples_tpu.web.gateway import _disagg_snapshot

        ep, ed, co = _pair(jax, seed=0)
        try:
            from modal_examples_tpu.serving import SamplingParams

            req = co.submit(PROMPT, SamplingParams(max_tokens=2))
            "".join(co.stream(req))
        finally:
            ed.stop()
        snap = _disagg_snapshot()
        assert snap["replicas"].get("pre-0") == "prefill"
        assert snap["migrations"]["pages"] > 0
        assert "tiers" in snap
