"""Example-corpus tests — the reference's internal/examples_test.py shape
(SURVEY.md §4): parametrize over every discovered example; each must have a
sane path, import cleanly, register an App, and render to non-empty docs."""

import importlib.util
import re
import sys

import pytest

from modal_examples_tpu.utils.docs import get_examples, render_example_md, repo_root

EXAMPLES = get_examples()
IDS = [str(e.path) for e in EXAMPLES]


def _import_example(example):
    path = repo_root() / example.path
    parent = str(path.parent)
    if parent not in sys.path:
        sys.path.insert(0, parent)
    spec = importlib.util.spec_from_file_location(
        f"example_{example.module_name}", path
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_examples_discovered():
    assert len(EXAMPLES) >= 10
    assert any(e.category == "01_getting_started" for e in EXAMPLES)


@pytest.mark.parametrize("example", EXAMPLES, ids=IDS)
def test_filename(example):
    assert re.match(r"^[a-z0-9_\-]+\.py$", example.path.name), example.path


@pytest.mark.parametrize("example", EXAMPLES, ids=IDS)
def test_import_and_app(example):
    import modal_examples_tpu as mtpu

    module = _import_example(example)
    apps = [v for v in vars(module).values() if isinstance(v, mtpu.App)]
    assert apps, f"{example.path} defines no App"
    assert apps[0].name.startswith("example-"), apps[0].name


@pytest.mark.parametrize("example", EXAMPLES, ids=IDS)
def test_render_docs(example):
    src = (repo_root() / example.path).read_text()
    md = render_example_md(src)
    assert len(md) > 100
    assert md.splitlines()[0].startswith("#"), "first line should be a heading"
