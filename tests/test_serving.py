"""Serving tests: allocator, sampling, continuous-batching engine, and the
OpenAI-compatible HTTP surface (health/models/completions/streaming — the
client contract from vllm_inference.py:243-345)."""

import json
import threading
import urllib.request

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def engine(jax):
    from modal_examples_tpu.models import llama
    from modal_examples_tpu.serving import LLMEngine

    cfg = llama.LlamaConfig.tiny()
    eng = LLMEngine(
        cfg, max_slots=4, max_model_len=128, page_size=16,
        prefill_buckets=(32, 64), seed=0,
    )
    yield eng
    eng.stop()


class TestAllocator:
    def test_alloc_free_cycle(self):
        from modal_examples_tpu.serving import OutOfPages, PageAllocator

        a = PageAllocator(8)  # page 0 reserved -> 7 usable
        pages = a.alloc(7)
        assert 0 not in pages
        with pytest.raises(OutOfPages):
            a.alloc(1)
        a.free(pages)
        assert a.available == 7


class TestSampling:
    def test_greedy_at_zero_temperature(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.serving import sample

        logits = jnp.array([[0.0, 5.0, 1.0], [3.0, 0.0, 1.0]])
        out = sample(
            logits, jax.random.PRNGKey(0),
            jnp.zeros(2), jnp.ones(2), jnp.zeros(2, jnp.int32),
        )
        assert out.tolist() == [1, 0]

    @pytest.mark.slow
    def test_top_k_masks_tail(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.serving import sample

        logits = jnp.array([[10.0, 9.0, -10.0, -10.0]])
        outs = {
            int(
                sample(
                    logits, jax.random.PRNGKey(i),
                    jnp.ones(1), jnp.ones(1), jnp.full(1, 2, jnp.int32),
                )[0]
            )
            for i in range(50)
        }
        assert outs <= {0, 1}

    @pytest.mark.slow
    def test_top_p_keeps_nucleus(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.serving import sample

        logits = jnp.array([[10.0, 1.0, 0.5, 0.1]])
        outs = {
            int(
                sample(
                    logits, jax.random.PRNGKey(i),
                    jnp.ones(1), jnp.full(1, 0.5), jnp.zeros(1, jnp.int32),
                )[0]
            )
            for i in range(50)
        }
        assert outs == {0}


class TestEngine:
    def test_generate_respects_max_tokens(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        req = engine.submit("hello", SamplingParams(max_tokens=5, temperature=1.0))
        text = "".join(engine.stream(req))
        n = len(engine.tokenizer.encode(text, add_bos=False))
        # n == 0 is legitimate: EOS can be the first sampled token
        assert n <= 5 + 1
        assert req.finish_reason in ("length", "stop")

    def test_greedy_deterministic(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        p = SamplingParams(max_tokens=8, temperature=0.0)
        a = engine.generate("determinism", p)
        b = engine.generate("determinism", p)
        assert a == b

    def test_continuous_batching_many_requests(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        # 2x oversubscribed vs slots: exercises admission + completion reuse
        reqs = [
            engine.submit(f"req {i}", SamplingParams(max_tokens=4, temperature=1.0))
            for i in range(8)
        ]
        outs = ["".join(engine.stream(r)) for r in reqs]
        assert len(outs) == 8

    def test_stop_safe_len_withholds_partial_stop(self):
        # OpenAI/vLLM contract: never emit a prefix of a stop string before
        # the match can resolve (stop='END' arriving token-wise as E,N,D)
        from modal_examples_tpu.serving.engine import _stop_safe_len

        assert _stop_safe_len("hello EN", ("END",)) == len("hello ")
        assert _stop_safe_len("hello E", ("END",)) == len("hello ")
        assert _stop_safe_len("hello ENX", ("END",)) == len("hello ENX")
        assert _stop_safe_len("hello", ()) == 5
        # multiple stops: the longest pending hold wins
        assert _stop_safe_len("abc<|e", ("<|end|>", "STOP")) == 3
        # (complete matches never reach here: the caller truncates via
        # text.find before computing the safe length)

    def test_stop_string_never_leaks_into_stream(self, jax):
        # end-to-end: patch detokenization so generation deterministically
        # walks through a stop string char by char; the stream must not
        # contain any prefix of it
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            page_size=16, prefill_buckets=(32,), seed=0,
        )
        script = "abETCdef"  # stop 'ETC' arrives split across steps
        eng.tokenizer.decode = lambda toks: script[: len(toks)]
        try:
            req = eng.submit(
                "x", SamplingParams(max_tokens=16, temperature=1.0, stop=("ETC",))
            )
            pieces = list(eng.stream(req))
            assert "".join(pieces) == "ab"
            assert req.finish_reason == "stop"
            for p in pieces:
                assert "E" not in p and "T" not in p and "C" not in p
        finally:
            eng.stop()

    def test_finish_reason_length_on_max_tokens(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        req = engine.submit("hi", SamplingParams(max_tokens=3, temperature=1.0))
        text = "".join(engine.stream(req))
        assert req.finish_reason in ("length", "stop")
        if req.finish_reason == "stop":
            # only legitimate if EOS actually fired before the cap
            n = len(engine.tokenizer.encode(text, add_bos=False))
            assert n < 3 + 1

    def test_stop_releases_inflight_callers(self, jax):
        """stop() must unblock stream()/generate() callers rather than
        leaving them waiting on a dead scheduler."""
        import threading

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(32,), seed=3,
        )
        eng.start()
        req = eng.submit("drain me", SamplingParams(max_tokens=10_000))
        got_out = threading.Event()

        def consume():
            for _ in eng.stream(req):
                pass
            got_out.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        import time

        time.sleep(0.5)  # let it start decoding
        eng.stop()
        assert got_out.wait(timeout=10), "stream() caller still blocked after stop()"

    def test_concurrent_client_threads(self, engine):
        """Many client threads submit/stream at once: the single scheduler
        thread must serve all without loss, duplication, or deadlock."""
        import threading

        from modal_examples_tpu.serving import SamplingParams

        engine.start()
        results: dict[int, str] = {}
        errors: list[BaseException] = []
        lock = threading.Lock()

        def client(i: int):
            try:
                out = engine.generate(
                    f"thread {i}", SamplingParams(max_tokens=3, temperature=1.0)
                )
                with lock:
                    results[i] = out
            except BaseException as e:
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        assert len(results) == 12

    def test_warmup_precompiles_all_shapes(self, jax):
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            prefill_buckets=(32,), seed=1,
        )
        try:
            t = eng.warmup()
            assert t > 0
            sizes = {
                b: fn._cache_size() for b, fn in eng._prefill_jits.items()
            }
            assert all(s >= 1 for s in sizes.values())
            decode_size = eng._block_jit._cache_size()
            assert decode_size >= 1
            # serving a request must NOT trigger new compiles
            eng.generate("warm", SamplingParams(max_tokens=2, temperature=0.0))
            assert eng._block_jit._cache_size() == decode_size
            assert all(
                fn._cache_size() == sizes[b]
                for b, fn in eng._prefill_jits.items()
            )
            # and warmup after start() is refused (donation race guard)
            with pytest.raises(RuntimeError, match="before start"):
                eng.warmup()
        finally:
            eng.stop()

    def test_abort_frees_slot(self, engine):
        from modal_examples_tpu.serving import SamplingParams

        req = engine.submit("abort me", SamplingParams(max_tokens=64, temperature=1.0))
        engine.start()
        engine.abort(req)
        out = "".join(engine.stream(req))  # must terminate promptly
        # all slots eventually free again
        import time

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if all(s.free for s in engine.slots):
                break
            time.sleep(0.05)
        assert all(s.free for s in engine.slots)

    def test_abort_queued_frees_reservation_and_depth(self, jax):
        """Regression (ISSUE 4 satellite): aborting a request that never
        reached a slot must free its reserved KV pages and decrement the
        queue-depth gauge immediately — without the scheduler thread ever
        running — and release the caller's stream."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.observability import catalog as C
        from modal_examples_tpu.serving import LLMEngine, SamplingParams
        from modal_examples_tpu.utils.prometheus import default_registry

        eng = LLMEngine(
            llama.LlamaConfig.tiny(), max_slots=2, max_model_len=64,
            page_size=16, prefill_buckets=(32,), seed=9,
        )
        try:
            req = eng.submit("never scheduled", SamplingParams(max_tokens=16))
            assert eng.policy.total_depth() == 1
            assert eng.admission.reserved_pages > 0
            assert default_registry.value(C.KV_PAGES_RESERVED) > 0
            eng.abort(req)
            assert eng.policy.total_depth() == 0
            assert eng.admission.reserved_pages == 0
            assert default_registry.value(C.KV_PAGES_RESERVED) == 0
            assert default_registry.value(
                C.SCHED_QUEUE_DEPTH, {"class": "default"}
            ) == 0
            # the stream terminates promptly (marker already queued)
            item = req.out_queue.get(timeout=5)
            assert hasattr(item, "reason")
            # and the page pool is untouched: nothing was ever claimed
            assert eng.cache.occupancy()["pages_used"] == 0
        finally:
            eng.stop()

    def test_seeded_sampling_deterministic_across_batches(self, engine):
        """A seeded request must sample identically whether it runs alone or
        alongside other traffic (the OpenAI `seed` contract)."""
        from modal_examples_tpu.serving import SamplingParams

        p = SamplingParams(max_tokens=6, temperature=1.0, seed=1234)
        alone = engine.generate("seeded prompt", p)
        # now with concurrent unseeded traffic sharing the batch
        noise = [
            engine.submit(f"noise {i}", SamplingParams(max_tokens=6, temperature=1.0))
            for i in range(3)
        ]
        busy = engine.generate("seeded prompt", p)
        for r in noise:
            "".join(engine.stream(r))
        assert alone == busy

    def test_unseeded_sampling_timing_independent(self, jax):
        """Unseeded requests auto-derive a seed from (engine seed, submission
        index): outputs depend only on the submission sequence, never on
        scheduler timing (how many blocks/keys the engine burned in between).
        This is the deflake contract — the old engine-key path made every
        temperature>0 test order- and load-dependent."""
        import time

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig.tiny()
        hot = SamplingParams(max_tokens=5, temperature=1.0)

        def run(churn):
            eng = LLMEngine(
                cfg, max_slots=2, max_model_len=64, page_size=16,
                prefill_buckets=(32,), seed=7,
            )
            outs = []
            for i in range(3):
                outs.append(eng.generate(f"prompt {i}", hot))
                if churn:
                    time.sleep(0.05)  # extra idle scheduler ticks
            eng.stop()
            return outs

        assert run(False) == run(True)

    def test_stats_accumulate(self, engine):
        assert engine.stats.generated_tokens > 0
        assert engine.stats.steps > 0


class TestOpenAIServer:
    @pytest.fixture(scope="class")
    def server(self, engine):
        from modal_examples_tpu.serving import OpenAIServer

        srv = OpenAIServer(engine, model_name="tiny-test", host="127.0.0.1", port=0)
        srv.start()
        yield srv
        srv.httpd.shutdown()

    def _url(self, server, path):
        return f"http://127.0.0.1:{server.port}{path}"

    def test_health_and_models(self, server):
        with urllib.request.urlopen(self._url(server, "/health")) as r:
            assert json.load(r)["status"] == "ok"
        with urllib.request.urlopen(self._url(server, "/v1/models")) as r:
            models = json.load(r)
        assert models["data"][0]["id"] == "tiny-test"

    def test_chat_completion(self, server):
        body = json.dumps(
            {
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4,
                "temperature": 0.0,
            }
        ).encode()
        req = urllib.request.Request(
            self._url(server, "/v1/chat/completions"),
            data=body,
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            out = json.load(r)
        assert out["object"] == "chat.completion"
        assert out["choices"][0]["message"]["role"] == "assistant"
        assert out["usage"]["prompt_tokens"] > 0

    def test_streaming_sse(self, server):
        body = json.dumps(
            {
                "messages": [{"role": "user", "content": "stream"}],
                "max_tokens": 4,
                "stream": True,
            }
        ).encode()
        req = urllib.request.Request(
            self._url(server, "/v1/chat/completions"),
            data=body,
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            payload = r.read().decode()
        assert payload.strip().endswith("data: [DONE]")
        chunks = [
            json.loads(line[6:])
            for line in payload.splitlines()
            if line.startswith("data: ") and line != "data: [DONE]"
        ]
        assert chunks and chunks[0]["object"] == "chat.completion.chunk"

    def test_n_choices(self, server):
        body = json.dumps(
            {
                "messages": [{"role": "user", "content": "pick"}],
                "max_tokens": 3,
                "n": 3,
                "temperature": 1.0,
            }
        ).encode()
        req = urllib.request.Request(
            self._url(server, "/v1/chat/completions"),
            data=body,
            headers={"content-type": "application/json"},
        )
        with urllib.request.urlopen(req) as r:
            out = json.load(r)
        assert [c["index"] for c in out["choices"]] == [0, 1, 2]
        assert all("content" in c["message"] for c in out["choices"])

    def test_metrics_endpoint(self, server):
        with urllib.request.urlopen(self._url(server, "/metrics")) as r:
            text = r.read().decode()
        assert "mtpu_generated_tokens_total" in text
        # the process registry's engine series (latency histograms) are part
        # of the exposition, and no metric name appears in both the
        # hand-built block and the registry block
        assert "mtpu_engine_phase_seconds_bucket" in text
        names = [
            l.split("{")[0].split(" ")[0]
            for l in text.splitlines()
            if l and not l.startswith("#")
        ]
        gauges = [n for n in names if n == "mtpu_active_slots"]
        assert len(gauges) == 1, "duplicate series between blocks"
