"""Tensor-parallel serving tests: the sharded dense-cache decode must equal
the single-device full forward, and params/cache must actually shard."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=64, dtype="float32",
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    return cfg, params, tokens


class TestDenseDecodeTP:
    def test_matches_forward_single_device(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, tokens = setup
        logits_full = llama.forward(params, tokens, cfg)
        want_next = np.argmax(np.asarray(logits_full[:, -1]), -1)

        out = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=1, max_len=32
        )
        np.testing.assert_array_equal(np.asarray(out[:, 16]), want_next)

    def test_matches_forward_on_tensor_mesh(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, tokens = setup
        mesh = make_mesh({"tensor": 2})
        logits_full = llama.forward(params, tokens, cfg)
        want_next = np.argmax(np.asarray(logits_full[:, -1]), -1)
        out = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=1,
            mesh=mesh, max_len=32,
        )
        np.testing.assert_array_equal(np.asarray(out[:, 16]), want_next)

    def test_params_and_cache_sharded(self, jax, setup):
        from jax.sharding import PartitionSpec as P

        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, _ = setup
        mesh = make_mesh({"tensor": 2})
        sharded = tp.shard_params_tp(params, cfg, mesh)
        assert sharded["layers"]["wq"].sharding.spec == P(None, None, "tensor")
        cache = tp.DenseKVCache.create(cfg, 2, 32, mesh)
        assert cache.k.sharding.spec == P(None, None, "tensor", None, None)

    def test_multi_token_greedy_generation(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, tokens = setup
        mesh = make_mesh({"tensor": 2})
        single = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=6, max_len=32
        )
        meshed = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=6,
            mesh=mesh, max_len=32,
        )
        np.testing.assert_array_equal(np.asarray(single), np.asarray(meshed))
