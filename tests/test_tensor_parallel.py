"""Tensor-parallel serving tests: the sharded dense-cache decode must equal
the single-device full forward, and params/cache must actually shard."""

import pytest

pytestmark = pytest.mark.slow  # heavyweight: excluded from the fast tier

import numpy as np


@pytest.fixture(scope="module")
def jax(jax_cpu):
    return jax_cpu


@pytest.fixture(scope="module")
def setup(jax):
    from modal_examples_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=64, dtype="float32",
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    return cfg, params, tokens


class TestDenseDecodeTP:
    def test_matches_forward_single_device(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, tokens = setup
        logits_full = llama.forward(params, tokens, cfg)
        want_next = np.argmax(np.asarray(logits_full[:, -1]), -1)

        out = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=1, max_len=32
        )
        np.testing.assert_array_equal(np.asarray(out[:, 16]), want_next)

    def test_matches_forward_on_tensor_mesh(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, tokens = setup
        mesh = make_mesh({"tensor": 2})
        logits_full = llama.forward(params, tokens, cfg)
        want_next = np.argmax(np.asarray(logits_full[:, -1]), -1)
        out = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=1,
            mesh=mesh, max_len=32,
        )
        np.testing.assert_array_equal(np.asarray(out[:, 16]), want_next)

    def test_params_and_cache_sharded(self, jax, setup):
        from jax.sharding import PartitionSpec as P

        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, _ = setup
        mesh = make_mesh({"tensor": 2})
        sharded = tp.shard_params_tp(params, cfg, mesh)
        assert sharded["layers"]["wq"].sharding.spec == P(None, None, "tensor")
        cache = tp.DenseKVCache.create(cfg, 2, 32, mesh)
        assert cache.k.sharding.spec == P(None, None, "tensor", None, None)

    def test_multi_token_greedy_generation(self, jax, setup):
        import jax.numpy as jnp

        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import tensor_parallel as tp

        cfg, params, tokens = setup
        mesh = make_mesh({"tensor": 2})
        single = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=6, max_len=32
        )
        meshed = tp.generate_tp(
            params, cfg, tokens, jnp.full((2,), 16), max_new=6,
            mesh=mesh, max_len=32,
        )
        np.testing.assert_array_equal(np.asarray(single), np.asarray(meshed))


class TestEngineTP:
    """Tensor parallelism as ONE engine flag (vllm_inference.py:180): the
    paged continuous-batching engine runs under a sharded jit — same
    scheduler, same OpenAI surface.

    Accuracy contract (docs/tensor_parallel.md, round 7): TP output is NOT
    token-exact vs single-device — row-parallel projections psum partial
    f32 sums in a different reduction order, and the ~1e-6 logit drift can
    flip a greedy argmax on these tiny random models (with the flash
    prefill kernel now running per head shard under shard_map, the drift
    surface is fixed by construction, not by partitioner luck). Single-vs-
    TP is therefore held to LOGIT tolerance; same-mesh pallas-vs-XLA
    token-exactness lives in tests/test_sharded_pallas.py."""

    def test_paged_engine_tp2_serves_and_shards(self, jax):
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])

        kw = dict(
            max_slots=2, max_model_len=64, page_size=16,
            prefill_buckets=(32,), seed=0, kv_dtype=jnp.float32,
        )
        tp = LLMEngine(cfg, params, mesh=mesh, **kw)
        try:
            prompts = ["sharded decode test", "one flag not a fork"]
            sp = SamplingParams(max_tokens=16, temperature=0.0)
            got = [tp.generate(p, sp) for p in prompts]
            assert all(got), got
            # deterministic: the same sharded program replays exactly
            assert got[0] == tp.generate(prompts[0], sp)
            assert tp.error_count == 0, tp.error_log
            # params and cache actually sharded over the tensor axis
            wq = tp.params["layers"]["wq"]
            assert len(wq.sharding.device_set) == 2
            assert len(tp.cache.k_pages.sharding.device_set) == 2
        finally:
            tp.stop()

    def test_paged_tp2_logit_drift_vs_single(self, jax):
        """The tolerance half of the TP contract for the plain f32 cache:
        prefill (sharded flash) + decode logits stay within the documented
        psum-reordering drift of the single-device run."""
        import functools

        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving.engine import _shard_params
        from modal_examples_tpu.serving.kv_cache import PagedKVCache

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 128)
        tables = jnp.asarray(
            1 + np.arange(2 * 4).reshape(2, 4), jnp.int32
        )
        seq_lens = jnp.array([12, 16], jnp.int32)
        active = jnp.ones((2,), bool)

        def run(p, mesh_arg):
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            cache = PagedKVCache.create(
                n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
                head_dim=cfg.head_dim, n_pages=9, page_size=16,
                kv_dtype=jnp.float32, prefer_native=False,
            )
            kp, vp = cache.k_pages, cache.v_pages
            if mesh_arg is not None:
                sh = NamedSharding(
                    mesh_arg, P(None, None, None, "tensor", None)
                )
                kp = jax.device_put(kp, sh)
                vp = jax.device_put(vp, sh)
            lo, kp, vp = jax.jit(
                functools.partial(
                    llama.prefill, cfg=cfg, attn_impl="flash", mesh=mesh_arg
                )
            )(p, toks, kp, vp, tables, seq_lens)
            nxt = jnp.argmax(lo, -1).astype(jnp.int32)
            l2, _, _ = jax.jit(
                functools.partial(
                    llama.decode_step, cfg=cfg, impl="xla", mesh=mesh_arg
                )
            )(p, nxt, seq_lens, kp, vp, tables, active)
            return np.asarray(lo), np.asarray(l2)

        lo_s, l2_s = run(params, None)
        lo_t, l2_t = run(_shard_params(params, cfg, mesh), mesh)
        assert float(np.max(np.abs(lo_t - lo_s))) < 1e-3
        assert float(np.max(np.abs(l2_t - l2_s))) < 1e-3

    def test_int8_kv_engine_tp2(self, jax):
        """int8 KV composes with tensor parallelism: the 4-leaf cache's
        scale arrays shard on the same kv-head axis as their int8 data
        (engine._shard_cache), so dequant never crosses chips. NOT a
        token-exact assertion like the bf16/f32 TP tests: a psum's
        ulp-level reduction reordering can flip an int8 rounding at a code
        boundary, so the contract is tolerance-based (docs/kv_cache.md) —
        checked on logits below; here the engine must boot, shard all four
        leaves, and generate cleanly."""
        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(7), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])

        tp = LLMEngine(
            cfg, params, mesh=mesh, max_slots=2, max_model_len=64,
            page_size=16, prefill_buckets=(32,), seed=0, kv_dtype="int8",
        )
        try:
            sp = SamplingParams(max_tokens=12, temperature=0.0)
            out = tp.generate("quantized cache sharded", sp)
            assert isinstance(out, str) and tp.error_count == 0
            # int8 payload AND f32 scale rows actually sharded
            kp = tp.cache.k_pages
            assert len(kp.data.sharding.device_set) == 2
            assert len(kp.scale.sharding.device_set) == 2
        finally:
            tp.stop()

    def test_int8_kv_tp2_logit_drift_vs_single(self, jax):
        """The tolerance half of the int8-KV TP contract: prefill + decode
        logits over the sharded quantized cache stay within the declared
        drift of the single-device quantized run (differences come only
        from psum reduction order at int8 code boundaries)."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving.engine import _shard_params
        from modal_examples_tpu.serving.kv_cache import PagedKVCache

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(8), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        toks = jax.random.randint(jax.random.PRNGKey(9), (2, 16), 0, 128)
        tables = jnp.asarray(
            1 + np.arange(2 * 4).reshape(2, 4), jnp.int32
        )
        seq_lens = jnp.array([12, 16], jnp.int32)
        active = jnp.ones((2,), bool)

        def run(p, shard):
            cache = PagedKVCache.create(
                n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
                head_dim=cfg.head_dim, n_pages=9, page_size=16,
                kv_dtype="int8", prefer_native=False,
            )
            if shard:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                from modal_examples_tpu.ops import QuantizedKV

                d = NamedSharding(mesh, P(None, None, None, "tensor", None))
                s = NamedSharding(mesh, P(None, None, None, "tensor"))
                for name in ("k_pages", "v_pages"):
                    pg = getattr(cache, name)
                    setattr(cache, name, QuantizedKV(
                        data=jax.device_put(pg.data, d),
                        scale=jax.device_put(pg.scale, s),
                    ))
            lo, kp, vp = llama.prefill(
                p, toks, cache.k_pages, cache.v_pages, tables, seq_lens,
                cfg, attn_impl="xla",
            )
            nxt = jnp.argmax(lo, -1).astype(jnp.int32)
            l2, _, _ = llama.decode_step(
                p, nxt, seq_lens, kp, vp, tables, active, cfg, impl="xla"
            )
            return np.asarray(lo), np.asarray(l2)

        lo_s, l2_s = run(params, shard=False)
        lo_t, l2_t = run(_shard_params(params, cfg, mesh), shard=True)
        assert float(np.max(np.abs(lo_t - lo_s))) < 0.25
        assert float(np.max(np.abs(l2_t - l2_s))) < 0.25

    def test_quantized_engine_tp2(self, jax):
        """int8 weight-only quantization composes with tensor parallelism
        (vLLM serves quantized TP): the TP engine serves cleanly and the
        QuantizedWeight payload AND its per-channel scales actually shard.
        Token equality vs single-device is deliberately not asserted (the
        psum-reordering contract in the class docstring)."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.models.quantize import QuantizedWeight
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(4), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])

        tp = LLMEngine(
            cfg, params, mesh=mesh, max_slots=2, max_model_len=64,
            page_size=16, prefill_buckets=(32,), seed=0,
            kv_dtype=jnp.float32, quantization="int8",
        )
        try:
            sp = SamplingParams(max_tokens=12, temperature=0.0)
            for p in ["quantized sharded decode", "int8 over two chips"]:
                assert tp.generate(p, sp), p
            assert tp.error_count == 0, tp.error_log
            wq = tp.params["layers"]["wq"]
            assert isinstance(wq, QuantizedWeight)
            assert len(wq.q.sharding.device_set) == 2
            assert len(wq.scale.sharding.device_set) == 2
        finally:
            tp.stop()

    def test_spec_decode_under_tp(self, jax):
        """Speculative decoding composes with tensor parallelism: the spec
        program (draft chain + verify + accept/reject) runs under the same
        sharded jit. With draft == target, greedy proposals must almost
        always match the target's argmax — the acceptance rate IS the
        spec-under-TP correctness signal (token equality vs a single-device
        engine is the psum lottery; class docstring)."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig(
            vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=128, dtype="float32",
        )
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        spec_tp = LLMEngine(
            cfg, params, mesh=mesh, speculative=(cfg, 2),
            draft_params=params, max_slots=2, max_model_len=64,
            page_size=16, prefill_buckets=(32,), seed=0,
            kv_dtype=jnp.float32,
        )
        try:
            sp = SamplingParams(max_tokens=12, temperature=0.0)
            got = spec_tp.generate("compose tp and spec", sp)
            assert got
            assert spec_tp.error_count == 0, spec_tp.error_log
            assert spec_tp.stats.acceptance_rate() > 0.9
        finally:
            spec_tp.stop()


class TestMoETensorParallel:
    def test_moe_engine_tp2_exact_match(self, jax):
        """MoE serving composes with TP (the reference's MoE targets run
        under --tp-size: sglang_low_latency.py's Qwen MoE,
        very_large_models.py's DeepSeek): the expert ffn dim shards over
        the tensor axis (llama.partition_specs) and the engine output must
        equal single-device token-for-token."""
        import jax.numpy as jnp

        from modal_examples_tpu.models import llama
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving import LLMEngine, SamplingParams

        cfg = llama.LlamaConfig.tiny_moe()
        assert cfg.n_experts > 0
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
        mesh = make_mesh({"tensor": 2}, devices=jax.devices()[:2])
        kw = dict(
            max_slots=2, max_model_len=64, page_size=16,
            prefill_buckets=(32,), seed=0, kv_dtype=jnp.float32,
        )
        single = LLMEngine(cfg, params, **kw)
        tp = LLMEngine(cfg, params, mesh=mesh, **kw)
        try:
            sp = SamplingParams(max_tokens=12, temperature=0.0)
            for p in ["moe sharded decode", "expert routing test"]:
                assert single.generate(p, sp) == tp.generate(p, sp), p
            # expert weights really sharded over the tensor axis
            up = tp.params["layers"]["moe_up"]
            assert len(up.sharding.device_set) == 2
        finally:
            single.stop()
            tp.stop()
