#!/usr/bin/env python
"""Compare two BENCH_r*.json files section-by-section and exit nonzero
past a regression threshold (ROADMAP #1's revalidation companion):

    python benchmarks/bench_diff.py BENCH_r03.json BENCH_r06.json
    python benchmarks/bench_diff.py old.json new.json --threshold 5

`tpurun benchdiff` is the installed entry point; the logic lives in
modal_examples_tpu/utils/bench_diff.py (jax-free) so both share one
implementation.
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from modal_examples_tpu.utils.bench_diff import run_diff  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(run_diff(sys.argv[1:]))
