#!/usr/bin/env python
"""Decode-step ablation: attribute per-step ms to weights / attention /
scatter / sampling.

decode_micro.py showed xla == pallas at every slot count (round 4), so the
paged-attention impl is NOT the bottleneck — this script finds what is, by
timing the same jitted decode block with components knocked out:

- ``full``      : llama.decode_step + fused sampling (what the engine runs)
- ``nosample``  : decode_step only; sampling replaced by argmax-free pass-through
- ``noattn``    : paged attention monkeypatched to identity -> XLA DCEs the
                  page gather AND the attention math (isolates weights+scatter)
- ``noscatter`` : noattn + the post-scan KV scatter dropped (pure weight chain)

Run: python benchmarks/decode_ablate.py [--quant int8] [--slots 8,16,32]
Prints one JSON line per (variant, slots).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama2-7b")
    from modal_examples_tpu.models.quantize import SUPPORTED

    ap.add_argument("--quant", default=None, choices=list(SUPPORTED))
    ap.add_argument("--slots", default="8,16,32")
    ap.add_argument("--variants", default="full,nosample,noattn,noscatter")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--kv-dtype", default="bfloat16")
    args = ap.parse_args()

    from modal_examples_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import jax

    if os.environ.get("BENCH_CPU"):
        # CPU smoke mode (the env-var platform route is unreliable once
        # the axon plugin is importable — pin explicitly)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.models.quantize import param_bytes
    from modal_examples_tpu.serving.sampling import sample
    from modal_examples_tpu.utils.sync import force

    cfg = (
        llama.LlamaConfig.tiny()
        if args.model == "tiny"
        else getattr(
            llama.LlamaConfig, args.model.replace("-", "_").replace(".", "")
        )()
    )
    if args.quant:
        from modal_examples_tpu.models.quantize import (
            bits_of, init_quantized_llama,
        )

        params = init_quantized_llama(
            jax.random.PRNGKey(0), cfg, bits=bits_of(args.quant)
        )
    else:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    force(params)
    weight_bytes = param_bytes(params)
    print(
        f"# {args.model} quant={args.quant} weights={weight_bytes/1e9:.2f} GB "
        f"floor={weight_bytes/819e9*1e3:.1f} ms/step",
        file=sys.stderr,
    )

    K = args.steps
    # "int8" = the QUANTIZED cache (int8 pages + f32 scale rows, the 4-leaf
    # QuantizedKV pytree) — not a plain int8 array, which no decode path
    # reads; any other value is a plain page dtype
    kv_dt = "int8" if args.kv_dtype == "int8" else jnp.dtype(args.kv_dtype)

    real_attn = llama.paged_decode_attention_inflight

    def fake_attn(q, ks, vs, prefix_lens, k_new, v_new, **kw):
        # ignores ks/vs -> XLA dead-code-eliminates the page gather entirely
        return q

    import contextlib

    @contextlib.contextmanager
    def attn_patched(on: bool):
        if on:
            llama.paged_decode_attention_inflight = fake_attn
        try:
            yield
        finally:
            llama.paged_decode_attention_inflight = real_attn

    def make_block(variant):
        do_sample = variant == "full"
        no_scatter = variant == "noscatter"

        def block(params, k_pages, v_pages, prev, positions, tables, active,
                  key, temps, top_ps, top_ks, seeds):
            def body(carry, k_i):
                tok, pos, kp, vp = carry
                logits, kp2, vp2 = llama.decode_step(
                    # impl pinned to the XLA inflight path: the noattn/
                    # noscatter DCE monkeypatch only works there
                    params, tok, pos, kp, vp, tables, active, cfg, impl="xla"
                )
                if no_scatter:
                    kp2, vp2 = kp, vp  # scatter result dropped -> DCE'd
                if do_sample:
                    nxt = sample(
                        logits, k_i, temps, top_ps, top_ks, seeds=seeds,
                        step_ids=pos,
                    )
                else:
                    # cheapest data-dependent token: keeps the scan sequential
                    nxt = logits[:, 0].astype(jnp.int32) % 17
                nxt = jnp.where(active, nxt, tok)
                return (nxt, pos + 1, kp2, vp2), nxt

            (last, _, k_pages, v_pages), toks = jax.lax.scan(
                body, (prev, positions, k_pages, v_pages),
                jax.random.split(key, K),
            )
            return toks, last, k_pages, v_pages

        return block

    for variant in args.variants.split(","):
        patch = variant in ("noattn", "noscatter")
        for slots in [int(s) for s in args.slots.split(",")]:
            pp = args.max_len // args.page_size
            n_pages = 1 + slots * pp
            try:
                with attn_patched(patch):
                    from modal_examples_tpu.ops import kv_empty

                    cache_shape = (
                        cfg.n_layers, n_pages, args.page_size,
                        cfg.n_kv_heads, cfg.head_dim,
                    )
                    kp = kv_empty(cache_shape, kv_dt)
                    vp = kv_empty(cache_shape, kv_dt)
                    tables = jnp.asarray(
                        1 + np.arange(slots * pp).reshape(slots, pp), jnp.int32
                    )
                    positions = jnp.full((slots,), args.max_len // 2, jnp.int32)
                    active = jnp.ones((slots,), bool)
                    prev = jnp.zeros((slots,), jnp.int32)
                    temps = jnp.ones((slots,), jnp.float32)
                    top_ps = jnp.ones((slots,), jnp.float32)
                    top_ks = jnp.zeros((slots,), jnp.int32)
                    seeds = jnp.arange(slots, dtype=jnp.int32)
                    fn = jax.jit(make_block(variant), donate_argnums=(1, 2))
                    t0 = time.time()
                    toks, last, kp, vp = fn(
                        params, kp, vp, prev, positions, tables, active,
                        jax.random.PRNGKey(1), temps, top_ps, top_ks, seeds,
                    )
                    np.asarray(last)  # block_until_ready is a no-op on axon
                    compile_s = time.time() - t0

                    def run(n):
                        nonlocal toks, last, kp, vp
                        t0 = time.time()
                        for i in range(n):
                            toks, last, kp, vp = fn(
                                params, kp, vp, last, positions, tables,
                                active, jax.random.PRNGKey(2 + i), temps,
                                top_ps, top_ks, seeds,
                            )
                        np.asarray(last)
                        return time.time() - t0

                    n1, n2 = max(2, args.iters // 3), args.iters
                    t1, t2 = run(n1), run(n2)
                    step_ms = (t2 - t1) / ((n2 - n1) * K) * 1e3
                    print(
                        json.dumps(
                            {
                                "variant": variant,
                                "slots": slots,
                                "step_ms": round(step_ms, 2),
                                "compile_s": round(compile_s, 1),
                            }
                        ),
                        flush=True,
                    )
                    del kp, vp
            except Exception as e:
                print(
                    json.dumps(
                        {"variant": variant, "slots": slots,
                         "error": f"{type(e).__name__}: {str(e)[:200]}"}
                    ),
                    flush=True,
                )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
