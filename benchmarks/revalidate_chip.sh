#!/bin/bash
# Round-4 chip revalidation (NOTES.md "Chip incident"): run ON A HEALTHY
# CHIP, in this order, each step in its own process so a wedge is
# attributable. Stop at the first hang and treat that step as the trigger.
set -x
cd "$(dirname "$0")/.."
# 0. health
timeout 120 python -c "import jax, jax.numpy as jnp; print(jax.devices()); print(float(jnp.ones(3).sum()))" || exit 1
# 1. pure-XLA decode path on the token-major layout
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8 --impl xla || exit 2
# 2. ragged attention kernel (v3)
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8,36 --impl pallas || exit 3
# 3. the pallas scatter kernel — the suspected round-4 wedge trigger
MTPU_SCATTER_IMPL=pallas timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8 --impl pallas || exit 4
# 4. int4 weights
timeout 900 python benchmarks/decode_micro.py --quant int4 --slots 8,36 --impl pallas || exit 5
# 5. full bench
timeout 1500 python bench.py || exit 6
