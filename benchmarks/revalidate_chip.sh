#!/bin/bash
# Round-5 chip revalidation (NOTES.md "Chip incident"): run ON A HEALTHY
# CHIP. Every Pallas kernel's first Mosaic compile goes through the
# kernel_probe harness (killable subprocess + hard timeout + result file),
# so a hang is killed and attributed instead of wedging the device claim.
# Stop at the first failing stage and treat it as the trigger.
set -x
set -o pipefail  # stage 12 pipes bench.py through tee: its exit must win
cd "$(dirname "$0")/.."
# every run leaves an attributable record (which stage ran/hung/failed)
LOG="benchmarks/revalidate_$(date -u +%Y%m%d_%H%M).log"
exec > >(tee "$LOG") 2>&1
# flight recorder (docs/observability.md): every stage's engines run under
# the tsdb sampler, so the minutes before a wedge survive on disk ...
export MTPU_TSDB=1
# correctness canary armed for the WHOLE run
# (docs/observability.md#correctness-canary): every stage's serving fleet
# probes its golden set at this cadence, so numeric drift anywhere in the
# revalidation fires canary_drift + an incident bundle instead of
# shipping a wrong-answer chip report; bench.py additionally emits its
# own record-then-compare `canary` section per config (stage 17c)
export MTPU_CANARY_INTERVAL=15
# ... and any stage failure ships an incident bundle (tsdb window, journal
# tails, compile ledger, env fingerprint) instead of a shrug: `fail CODE
# "STAGE"` captures, prints the bundle path in the stage summary, exits.
fail() {
  local code="$1"
  BUNDLE=$(timeout 120 python -m modal_examples_tpu incident capture \
    --trigger stage_failure \
    --reason "revalidate_chip stage failed (exit code ${code})" 2>/dev/null | tail -1)
  echo "revalidate_chip FAILED (exit code ${code}) — incident bundle: ${BUNDLE:-capture failed}"
  exit "${code}"
}
# 0. health
timeout 120 python -c "import jax, jax.numpy as jnp; print(jax.devices()); print(float(jnp.ones(3).sum()))" || fail 1
# 1. every kernel, tiny shapes, one killable subprocess each; registry
#    order puts the round-4 wedge suspect (scatter_kv) LAST
python -m modal_examples_tpu.utils.kernel_probe --all --timeout 600 || fail 2
# 2. pure-XLA decode path on the token-major layout
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8 --impl xla || fail 3
# 3. ragged attention kernel (v3) at real shapes
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8,36 --impl pallas || fail 4
# 4. the pallas scatter at real shapes
MTPU_SCATTER_IMPL=pallas timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8 --impl pallas || fail 5
# 5. int4 weights
timeout 900 python benchmarks/decode_micro.py --quant int4 --slots 8,36 --impl pallas || fail 6
# 6. GQA on the grouped ragged kernel (llama-3.1 head geometry) + the
#    flat-vs-grouped A/B at the 7B MHA shape
timeout 1500 python benchmarks/decode_micro.py --model llama3.1-8b --quant int8 --slots 8,32 --impl pallas || fail 7
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 32 --impl pallas --variant grouped || fail 8
# 7. int8 KV cache (new Mosaic paths: int8 page + scale-row DMAs, in-VMEM
#    dequant — probed first via --probe) — the bf16-vs-int8 KV A/B at the
#    headline shape, then the long-context config where KV reads dominate
timeout 900 python benchmarks/decode_micro.py --probe --quant int8 --slots 32 --impl pallas --kv-dtype int8 || fail 9
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8,16 --max-len 1024 --impl pallas --kv-dtype bf16 || fail 10
timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8,16 --max-len 1024 --impl pallas --kv-dtype int8 || fail 11
# 8. two-replica disagg smoke: the ctx-1024 int8-KV config unified, then the
#    same shape disaggregated (prefill replica shipping int8 pages + scale
#    rows to the decode replica, weights shared) — the A/B that prices page
#    migration on real hardware (docs/disagg.md)
timeout 1500 env BENCH_MODEL=llama2-7b-int8-kv8-ctx1024 BENCH_NO_SECONDARY=1 python bench.py || fail 12
timeout 1500 env BENCH_MODEL=llama2-7b-disagg-2rep BENCH_NO_SECONDARY=1 python bench.py || fail 13
# 9. tensor parallelism (TP=2) on the sharded pallas fast path (round 7,
#    ops.sharded): pallas-vs-xla A/B at bf16 and int8 KV — per-shard Hkv=16
#    compiles ride the probe harness (stage 1 covers
#    ragged_decode_tp_shard_int8kv) — then the ctx-1024 int8 TP bench
#    config, the ROADMAP-named A/B partner of stage 7's single-chip run.
#    Gated on device count: a 1-chip host SKIPS these stages (the later
#    single-chip stages must still run) instead of aborting the script.
if timeout 120 python -c "import jax; raise SystemExit(0 if len(jax.devices()) >= 2 else 1)"; then
  timeout 900 python benchmarks/decode_micro.py --quant int8 --slots 8 --tp 2 --impl xla,pallas --kv-dtype bf16 || fail 14
  timeout 900 python benchmarks/decode_micro.py --probe --quant int8 --slots 8 --tp 2 --impl xla,pallas --kv-dtype int8 || fail 15
  timeout 1500 env BENCH_MODEL=llama2-7b-tp2-int8-ctx1024 BENCH_NO_SECONDARY=1 python bench.py || fail 16
else
  echo "stage 9 SKIPPED: fewer than 2 devices (TP stages need a multi-chip host)"
fi
# 10. speculative decoding as a measured lever (ROADMAP open item #4): the
#     ngram config (acceptance-driven win) vs its no-spec A/B partner
#     llama2-7b-int8-kv8-s36 from the full bench below
timeout 1500 env BENCH_MODEL=llama2-7b-int8-spec-ngram BENCH_NO_SECONDARY=1 python bench.py || fail 17
# 10b. fused ADAPTIVE speculation A/B at the int8 headline shape
#      (docs/speculative.md#gamma-schedule), behind the regression gate:
#      spec-off vs fixed-γ vs the acceptance-driven controller on the same
#      warm engine via the runtime-mutable spec_depth/spec_adaptive knobs —
#      the json's `spec` section carries per-arm TPOT tails plus
#      gamma_p50/tokens_per_dispatch/fallback_rounds; bench_diff's
#      spec.tokens_per_dispatch and spec.adaptive_vs_off_tpot_p95 gate it
#      from the next round on (the latter must hold ~>=1: adaptivity may
#      never be slower than not speculating)
timeout 1500 env BENCH_MODEL=llama2-7b-int8-spec-adaptive BENCH_NO_SECONDARY=1 python bench.py || fail 30
# 11. stall-free admission under mixed traffic (round 10, docs/scheduling.md):
#     the ctx-1024 int8 shape with an interactive stream decoding while
#     ~1k-token prompts chunk-prefill — budgeted (256 tok/tick = one chunk)
#     vs unbudgeted TPOT in the json's `interference` section, plus the
#     mtpu_decode_stall_seconds dispatch-gap quantiles
timeout 1500 env BENCH_MODEL=llama2-7b-mixed-ctx1024 BENCH_NO_SECONDARY=1 python bench.py || fail 18
# 12. full bench (kv_cache + disagg + spec + tp + interference sections),
#     captured to a file for the regression gate below
timeout 1500 python bench.py | tee benchmarks/BENCH_revalidate.json || fail 19
# 13. round-over-round regression gate (ROADMAP #1): diff the fresh json
#     against the newest committed BENCH_r*.json — tok/s, ttft/tpot p95,
#     shed rate, migration p95, interference p95 — and FAIL loudly past
#     15% instead of relying on eyeballs
PREV=$(ls BENCH_r*.json 2>/dev/null | sort | tail -1)
if [ -n "$PREV" ]; then
  python -m modal_examples_tpu benchdiff "$PREV" benchmarks/BENCH_revalidate.json --threshold 15 || fail 20
else
  echo "stage 13 SKIPPED: no BENCH_r*.json to diff against"
fi
# 13b. macro-step decode A/B at the int8 headline shape (docs/multistep.md),
#      behind the regression gate: N=1 vs N=8 on the same warm engine via
#      the runtime-mutable decode_steps knob — the json's `multistep`
#      section carries per-arm host_fraction/tick_p95/tokens-per-dispatch
#      and the deltas; bench_diff's multistep.tokens_per_dispatch gates it
#      from the next round on. On chip the N=8 arm's host_fraction must
#      drop outright (each dispatch carries ~8x device work for the same
#      host bookkeeping)
timeout 1500 env BENCH_MODEL=llama2-7b-int8-multistep BENCH_NO_SECONDARY=1 python bench.py || fail 29
# 14. closed-loop fleet sweep (docs/fleet.md), behind the regression gate:
#     the int8 headline shape under production-shaped open-loop traffic —
#     calibrated saturating sweep, pinned single replica vs FleetAutoscaler
#     growing a second replica via snapshot-restored warm boot; the json's
#     `fleet` section (goodput, p99 TTFT/TPOT vs offered load, shed rate,
#     scale events, A/B at the knee) is what bench_diff's fleet.* metrics
#     gate from the next round on
timeout 1500 env BENCH_MODEL=llama2-7b-fleet-sweep BENCH_NO_SECONDARY=1 python bench.py | tee benchmarks/BENCH_fleet.json || fail 21
# 14b. shared prefix-store A/B (docs/prefix_store.md), inside stage 14's
#      `fleet` section: two replicas over private vs fleet-wide volume
#      tiers — the shared arm must actually dedup (ratio > 1.0: replica
#      B's spill skipped what replica A already wrote) and serve the cold
#      replica from peer spills; fleet.shared_prefix_ttft_p95 gates via
#      benchdiff from the next round on
timeout 120 python - <<'PYEOF' || fail 26
from modal_examples_tpu.utils.bench_diff import load_bench
sp = load_bench("benchmarks/BENCH_fleet.json")["fleet"]["shared_prefix"]
assert sp["shared"]["dedup_ratio"] > 1.0, sp
assert sp["shared"]["peer_hits"] > 0, sp
assert sp["shared"]["ttft_p95"] > 0, sp
print(f"stage 14b: shared prefix store OK — dedup={sp['shared']['dedup_ratio']}"
      f" peer_hits={sp['shared']['peer_hits']}"
      f" ttft_p95_vs_private={sp['ttft_p95_vs_private']}")
PYEOF
# 15. in-flight failover at the int8 headline shape (docs/failover.md),
#     behind the regression gate: streams killed mid-decode and
#     checkpoint-resumed on a second replica (weights aliased) — the
#     json's `failover` section (takeover p50/p95, tokens_replayed,
#     resumed_identical: true) is what bench_diff's
#     failover.takeover_latency.p95 gates from the next round on
timeout 1500 env BENCH_MODEL=llama2-7b-failover BENCH_NO_SECONDARY=1 python bench.py || fail 22
# 16. gray-failure recovery at the int8 headline shape (docs/health.md),
#     behind the regression gate: a replica's scheduler SILENTLY frozen
#     with streams mid-decode — the progress watchdog detects the wedge
#     from stale watermarks, error-stops the replica, and the failover
#     resumes every stream token-identically; the json's `recovery`
#     section (time_to_detect / time_to_mitigate p50/p95, goodput_dip,
#     wedged: 0) is what bench_diff's recovery.time_to_mitigate.p95 gates
#     from the next round on
timeout 1500 env BENCH_MODEL=llama2-7b-recovery BENCH_NO_SECONDARY=1 python bench.py || fail 24
# 17. hot-path overhead attribution at the int8 headline shape (ROADMAP #3,
#     docs/observability.md#hot-path-profiling), behind the regression
#     gate: bench children profile by default (MTPU_PROFILE=1), so stage
#     12's full run ALREADY measured the headline config's `overhead`
#     section (host_fraction, per-phase tick p50/p95, detok_share, compile
#     totals) on real hardware, and stage 13's benchdiff gates
#     overhead.host_fraction / overhead.tick_p95 from the next round on —
#     the host-vs-device split is the BASELINE the multi-step decode PR
#     must shrink. This stage validates + extracts that artifact instead
#     of paying a duplicate ~25-minute headline run.
timeout 120 python - <<'PYEOF' || fail 25
import json
from modal_examples_tpu.utils.bench_diff import load_bench
ov = load_bench("benchmarks/BENCH_revalidate.json")["overhead"]
assert ov["ticks"] > 0 and ov["host_fraction"] is not None, ov
assert ov["tick_p95"] is not None and ov["phases"], ov
json.dump(ov, open("benchmarks/BENCH_overhead.json", "w"), indent=1)
print(f"stage 17: overhead section OK — host_fraction={ov['host_fraction']}"
      f" tick_p95={ov['tick_p95']} compiles={ov['compiles_n']}")
PYEOF
# 17b. roofline utilization accounting (docs/observability.md#roofline-and-
#      usage-accounting): stage 12's full run already emitted the
#      `utilization` section — analytic-model MFU/MBU against the chip's
#      peaks plus the compute-vs-bandwidth classification (decode serving
#      must classify bandwidth-bound on real hardware; MBU here vs the
#      pct_hbm_ceiling weight-streaming bound is the honest-accounting
#      cross-check). benchdiff gates utilization.mfu / utilization.mbu /
#      utilization.tokens_per_second_per_chip from the next round on.
timeout 120 python - <<'PYEOF' || fail 27
import json
from modal_examples_tpu.utils.bench_diff import load_bench
ut = load_bench("benchmarks/BENCH_revalidate.json")["utilization"]
assert 0.0 < ut["mfu"] <= 1.5, ut   # >1 means the work model or clock lies
assert 0.0 < ut["mbu"] <= 1.5, ut
assert ut["bound"] in ("compute", "bandwidth"), ut
assert ut["tokens_per_second_per_chip"] > 0, ut
assert ut["per_phase"]["decode"]["device_seconds"] > 0, ut
json.dump(ut, open("benchmarks/BENCH_utilization.json", "w"), indent=1)
print(f"stage 17b: utilization section OK — mfu={ut['mfu']} mbu={ut['mbu']}"
      f" bound={ut['bound']} tok/s/chip={ut['tokens_per_second_per_chip']}")
PYEOF
# 17c. correctness canary (docs/observability.md#correctness-canary):
#      stage 12's full run recorded-then-compared the golden set on the
#      headline config's warm engine — the `canary` section must show
#      zero drift and zero probe errors on a healthy chip, and the
#      fingerprint proves the golden was recorded on THIS numeric
#      identity (a CPU-recorded golden can never gate this run)
timeout 120 python - <<'PYEOF' || fail 28
from modal_examples_tpu.utils.bench_diff import load_bench
cn = load_bench("benchmarks/BENCH_revalidate.json")["canary"]
assert cn["drift_count"] == 0, cn
assert cn["errors"] == 0, cn
assert cn["pass_rate"] == 1.0, cn
assert cn["probes"] > 0 and cn["fingerprint"], cn
print(f"stage 17c: canary section OK — probes={cn['probes']}"
      f" pass_rate={cn['pass_rate']} drift={cn['drift_count']}"
      f" ttft_p95={cn['ttft_p95']} fp={cn['fingerprint']}")
PYEOF
# 18. compile ledger for the >=40-slot compile-helper ceiling (ROADMAP #1,
#     docs/observability.md#hot-path-profiling): run the s44 config with
#     the hot-path profiler ON and a LOCAL state dir. The profiler writes
#     a `begin` ledger event before every program build, so when the
#     remote-compile helper crashes/hangs past ~40 slots the ledger's
#     begin-without-end row names the exact program/shape — the repro
#     ships offline-diagnosable (`tpurun profile --dir benchmarks/profile_state`).
#     LAST on purpose: this config wedged the chip in round 4, and every
#     earlier stage assumes a healthy device — running it here means a
#     wedge poisons nothing and the round's other results stand. The s44
#     program shapes are unique to this config (no other config runs >=40
#     slots), so nothing earlier warms its compiles. Non-fatal: failure at
#     the ceiling is the expected outcome; the ledger is the artifact.
mkdir -p benchmarks/profile_state
# fresh ledger each round: revalidate appends otherwise, and a stale
# round's begin/end rows would inflate compile totals in the artifact
rm -f benchmarks/profile_state/compiles.jsonl
if MTPU_STATE_DIR=benchmarks/profile_state timeout 1500 \
    env BENCH_MODEL=llama2-7b-int8-s44 BENCH_NO_SECONDARY=1 python bench.py; then
  echo "stage 18: s44 ran clean — the compile ceiling may have moved; ledger captured anyway"
else
  echo "stage 18: s44 failed at the compile ceiling (expected) — see benchmarks/profile_state/compiles.jsonl"
fi
cp benchmarks/profile_state/compiles.jsonl benchmarks/compiles_s44.jsonl 2>/dev/null || true
