#!/usr/bin/env python
"""First real-chip validation of every Pallas kernel (VERDICT round-1 weak #3).

Round 1 verified all kernels in interpreter mode on CPU only. This script
compiles each kernel via Mosaic on the attached TPU, checks numerics against
the pure-XLA references at bf16 tolerances, and times kernel vs XLA. One
section per kernel; a section failure doesn't stop the rest. Prints a JSON
summary line at the end.

Run: python benchmarks/validate_mosaic.py  (expects a healthy TPU; ~2 min)
"""

from __future__ import annotations

import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from modal_examples_tpu import ops
from modal_examples_tpu.ops import reference

RESULTS: dict[str, dict] = {}


def section(name):
    def deco(fn):
        t0 = time.time()
        try:
            out = fn() or {}
            out["ok"] = True
        except Exception as e:
            traceback.print_exc()
            out = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        out["wall_s"] = round(time.time() - t0, 1)
        RESULTS[name] = out
        print(f"[{name}] {out}", flush=True)
        return fn

    return deco


def timeit(fn, *args, iters=20):
    # force(), not block_until_ready: the latter is a no-op on the tunneled
    # axon backend, so these timings would otherwise measure dispatch only
    from modal_examples_tpu.utils.sync import force

    force(fn(*args))
    t0 = time.time()
    for _ in range(iters):
        r = fn(*args)
    force(r)
    return (time.time() - t0) / iters * 1e3  # ms


def main():
    if "--no-probe" not in sys.argv:
        # wedge-proof rule: every kernel's first Mosaic compile happens in
        # a killable subprocess (utils/kernel_probe) BEFORE this process
        # attaches the chip; a hang is killed and attributed instead of
        # wedging the session's device claim (rounds 1 + 4 postmortem)
        from modal_examples_tpu.utils.kernel_probe import run_probes

        results = run_probes(timeout_s=600)
        bad = {k: r.status for k, r in results.items() if not r.ok}
        if bad:
            print(json.dumps({"probe_failed": bad}), flush=True)
            return 2
    assert jax.default_backend() == "tpu", jax.default_backend()
    print("device:", jax.devices()[0], flush=True)

    key = jax.random.PRNGKey(0)
    B, Hq, Hkv, S, D = 4, 32, 8, 1024, 128
    q = jax.random.normal(key, (B, Hq, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, Hkv, S, D), jnp.bfloat16)

    @section("flash_fwd")
    def _():
        flash = jax.jit(ops.flash_attention)
        ref = jax.jit(lambda q, k, v: reference.attention(q, k, v))
        o1 = flash(q, k, v)
        o2 = ref(q, k, v)
        err = float(jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32))))
        assert err < 0.06, err
        ms_flash = timeit(flash, q, k, v)
        ms_ref = timeit(ref, q, k, v)
        # causal attention flops: 2 matmuls, half the square
        flops = 2 * 2 * B * Hq * S * S * D / 2
        return {
            "max_err": round(err, 4),
            "pallas_ms": round(ms_flash, 3),
            "xla_ms": round(ms_ref, 3),
            "pallas_tflops": round(flops / ms_flash / 1e9, 1),
        }

    @section("flash_bwd")
    def _():
        def loss_flash(q, k, v):
            return jnp.sum(ops.flash_attention(q, k, v).astype(jnp.float32))

        def loss_ref(q, k, v):
            return jnp.sum(reference.attention(q, k, v).astype(jnp.float32))

        g1 = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
        g2 = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))
        r1 = g1(q, k, v)
        r2 = g2(q, k, v)
        errs = [
            float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
            for a, b in zip(r1, r2)
        ]
        assert max(errs) < 1.0, errs  # bf16 sum-of-S grads; scale ~sqrt(S)
        ms_flash = timeit(lambda *a: g1(*a)[0], q, k, v, iters=10)
        ms_ref = timeit(lambda *a: g2(*a)[0], q, k, v, iters=10)
        return {
            "max_err": round(max(errs), 4),
            "pallas_ms": round(ms_flash, 3),
            "xla_ms": round(ms_ref, 3),
        }

    @section("flash_chunked")
    def _():
        q_off = 512
        qc = q[:, :, :256, :]
        fn = jax.jit(
            lambda qc, k, v: ops.flash_attention_chunked(qc, k, v, q_offset=q_off)
        )
        o1 = fn(qc, k, v)
        # reference: rows [q_off, q_off+256) of full causal attention with
        # the chunk's queries substituted at those positions
        qfull = q.at[:, :, q_off : q_off + 256, :].set(qc)
        o2 = jax.jit(lambda q, k, v: reference.attention(q, k, v))(qfull, k, v)[
            :, :, q_off : q_off + 256, :
        ]
        err = float(jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32))))
        assert err < 0.06, err
        return {"max_err": round(err, 4), "ms": round(timeit(fn, qc, k, v), 3)}

    @section("paged_decode")
    def _():
        page_size, pages_per_seq = 16, 32
        n_pages = B * pages_per_seq + 8
        kp = jax.random.normal(
            jax.random.PRNGKey(3), (n_pages, page_size, Hkv, D), jnp.bfloat16
        )
        vp = jax.random.normal(
            jax.random.PRNGKey(4), (n_pages, page_size, Hkv, D), jnp.bfloat16
        )
        pt = jax.random.permutation(jax.random.PRNGKey(5), n_pages)[
            : B * pages_per_seq
        ].reshape(B, pages_per_seq).astype(jnp.int32)
        lens = jnp.array([100, 512, 37, 480], jnp.int32)
        qd = jax.random.normal(jax.random.PRNGKey(6), (B, Hq, D), jnp.bfloat16)
        import functools

        # impl="pallas" explicitly: the default is the XLA gather path, and
        # this script exists to validate the Mosaic-compiled kernel on chip
        fn = jax.jit(functools.partial(ops.paged_decode_attention, impl="pallas"))
        xlafn = jax.jit(functools.partial(ops.paged_decode_attention, impl="xla"))
        refn = jax.jit(reference.paged_decode_attention)
        o1 = fn(qd, kp, vp, pt, lens)
        o2 = refn(qd, kp, vp, pt, lens)
        o3 = xlafn(qd, kp, vp, pt, lens)
        err = float(jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32))))
        err_xla = float(
            jnp.max(jnp.abs(o3.astype(jnp.float32) - o2.astype(jnp.float32)))
        )
        assert err < 0.06, err
        assert err_xla < 0.06, err_xla
        return {
            "max_err": round(err, 4),
            "max_err_xla": round(err_xla, 4),
            "pallas_ms": round(timeit(fn, qd, kp, vp, pt, lens), 3),
            "xla_ms": round(timeit(xlafn, qd, kp, vp, pt, lens), 3),
        }

    @section("quantized_matmul")
    def _():
        M, K, N = 1024, 4096, 4096
        x = jax.random.normal(jax.random.PRNGKey(7), (M, K), jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(8), (K, N), jnp.float32)
        w_q, w_scale = ops.quantize_int8(w)
        fn = jax.jit(ops.quantized_matmul)
        o1 = fn(x, w_q, w_scale)
        o2 = jnp.dot(
            x.astype(jnp.float32), ops.dequantize_int8(w_q, w_scale)
        ).astype(x.dtype)
        err = float(
            jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32)))
        )
        rel = err / float(jnp.max(jnp.abs(o2.astype(jnp.float32))) + 1e-6)
        assert rel < 0.05, (err, rel)
        ms_q = timeit(fn, x, w_q, w_scale)
        bf16 = jax.jit(lambda x, w: jnp.dot(x, w.astype(jnp.bfloat16)))
        ms_bf16 = timeit(bf16, x, w)
        return {
            "rel_err": round(rel, 4),
            "pallas_int8_ms": round(ms_q, 3),
            "xla_bf16_ms": round(ms_bf16, 3),
        }

    n_ok = sum(1 for r in RESULTS.values() if r["ok"])
    print(
        json.dumps(
            {
                "mosaic_validation": RESULTS,
                "ok": n_ok,
                "total": len(RESULTS),
                "device": str(jax.devices()[0]),
            }
        ),
        flush=True,
    )
    return 0 if n_ok == len(RESULTS) else 1


if __name__ == "__main__":
    raise SystemExit(main())
