#!/usr/bin/env python
"""Raw decode-block microbench: per-step device time vs the weight floor.

Times the engine's jitted decode block (llama.decode_step scanned
``decode_block`` times + fused sampling — exactly what LLMEngine dispatches)
WITHOUT the scheduler, so the number is pure device time. Sweeps slot
counts / quantization / decode structure to answer:

1. how far is a decode step from the weight-streaming floor
   (weights / 819 GB/s — 16.5 ms bf16, 8.4 ms int8 at 7B)?
2. which ``MTPU_PAGED_IMPL`` structure wins (``xla`` = round-3 read-only
   pages + one scatter; ``xla-writeback`` = round-2 per-layer cache writes
   threaded through the scan; ``pallas`` = hand kernel)?
3. where is the slot-count OOM boundary for each weight dtype?

Run: python benchmarks/decode_micro.py [--quant int8] [--slots 8,16,24,32]
     [--impl xla,xla-writeback] [--model llama2-7b] [--steps 8]
Prints one JSON line per (impl, slots) config; OOM prints an error entry.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    # CPU TP smoke (--tp N + BENCH_CPU) needs virtual devices BEFORE any
    # jax import — and the argparse setup below already imports the package
    tp_requested = any(
        a == "--tp" or a.startswith("--tp=") for a in sys.argv
    )
    if tp_requested and os.environ.get("BENCH_CPU"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama2-7b")
    from modal_examples_tpu.models.quantize import SUPPORTED

    ap.add_argument("--quant", default=None, choices=list(SUPPORTED))
    ap.add_argument("--slots", default="8,16,32")
    ap.add_argument("--impl", default="xla,xla-writeback")
    ap.add_argument(
        # no None in choices: argparse compares the PARSED string against
        # choices, so None only ever matched by being the default — listing
        # it rejected an explicit "--variant" while implying it was valid
        "--variant", default=None, choices=["flat", "grouped"],
        help="ragged-kernel formulation A/B (impl=pallas): flat = v3 "
        "all-heads matmul, grouped = v4 per-kv-head (GQA-capable); "
        "default: auto by head geometry + kv dtype",
    )
    ap.add_argument(
        "--kv-dtype", default="bf16", choices=["bf16", "int8"],
        help="page-cache dtype A/B: int8 = quantized KV (int8 pages + f32 "
        "scale rows — half the KV HBM traffic and residency, "
        "docs/kv_cache.md)",
    )
    ap.add_argument(
        "--tp", type=int, default=1,
        help="tensor-parallel degree: weights take the Megatron specs, the "
        "cache shards by kv head, and the pallas impls run per head shard "
        "via ops.sharded's shard_map dispatch (round 7) — the TP A/B lever "
        "for revalidate_chip.sh",
    )
    ap.add_argument("--steps", type=int, default=8, help="decode_block")
    ap.add_argument("--max-len", type=int, default=256)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument(
        "--probe", action="store_true",
        help="bring up the needed Pallas kernels in killable subprocesses "
        "first (the wedge-proof rule — run this for any first-on-chip "
        "compile of a new/changed kernel)",
    )
    args = ap.parse_args()

    if args.probe:
        # probes claim the chip from their own subprocesses, so they must
        # finish before this process attaches (single tunneled chip)
        from modal_examples_tpu.utils.kernel_probe import run_probes

        # only the kernels this bench will actually trace: the quantized
        # decode path upcasts through plain jnp.dot (layers.mm), so no
        # int8_matmul probe is needed for --quant. The ragged probe must
        # match the VARIANT this model's head geometry selects — probing
        # flat for a GQA run would leave the grouped kernel's first Mosaic
        # compile in-process, defeating the wedge-proof rule.
        from modal_examples_tpu.models import llama as _llama
        from modal_examples_tpu.ops.paged_attention import ragged_variant_for

        _cfg = (
            _llama.LlamaConfig.tiny()
            if args.model == "tiny"
            else getattr(
                _llama.LlamaConfig,
                args.model.replace("-", "_").replace(".", ""),
            )()
        )
        needed = []
        if "pallas" in args.impl:
            kvd = "int8" if args.kv_dtype == "int8" else "bfloat16"
            # under --tp the kernel compiles at the SHARD-local head count
            # (Hkv // tp), so the probed variant must match that shape
            tp = max(1, args.tp)
            hkv = _cfg.n_kv_heads // tp if _cfg.n_kv_heads % tp == 0 else (
                _cfg.n_kv_heads
            )
            variant = args.variant or ragged_variant_for(hkv, kvd)
            suffix = "_int8kv" if args.kv_dtype == "int8" else ""
            hq_shard = (
                _cfg.n_heads // tp if _cfg.n_heads % tp == 0 else _cfg.n_heads
            )
            if suffix and variant == "grouped" and hq_shard == hkv == 16:
                # MHA-as-grouped at the TP=2 7B shard shape (Hq=Hkv=16,
                # G=1) is its own Mosaic shape family with a dedicated
                # registry probe — first compiles stay in the killable
                # harness (the wedge-proof rule). Other shard shapes fall
                # through to the generic variant probes below (same
                # approximation level single-chip GQA shapes already use).
                needed.append("ragged_decode_tp_shard_int8kv")
            else:
                needed.append(
                    (
                        "ragged_decode"
                        if variant == "flat"
                        else "ragged_decode_gqa"
                    )
                    + suffix
                )
        if os.environ.get("MTPU_SCATTER_IMPL") == "pallas":
            needed.append(
                "scatter_kv_int8" if args.kv_dtype == "int8" else "scatter_kv"
            )
        results = run_probes(needed, timeout_s=600)
        bad = {k: r.status for k, r in results.items() if not r.ok}
        if bad:
            print(json.dumps({"probe_failed": bad}), flush=True)
            return 2

    from modal_examples_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import jax

    if os.environ.get("BENCH_CPU"):
        # CPU smoke mode (the env-var platform route is unreliable once
        # the axon plugin is importable — pin explicitly)
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from modal_examples_tpu.models import llama
    from modal_examples_tpu.models.quantize import param_bytes
    from modal_examples_tpu.serving.sampling import sample

    from modal_examples_tpu.utils.sync import force

    cfg = (
        llama.LlamaConfig.tiny()
        if args.model == "tiny"
        else getattr(llama.LlamaConfig, args.model.replace("-", "_").replace(".", ""))()
    )
    t0 = time.time()
    if args.quant:
        from modal_examples_tpu.models.quantize import (
            bits_of, init_quantized_llama,
        )

        params = init_quantized_llama(
            jax.random.PRNGKey(0), cfg, bits=bits_of(args.quant)
        )
    else:
        params = llama.init_params(jax.random.PRNGKey(0), cfg)
    mesh = None
    if args.tp > 1:
        from modal_examples_tpu.parallel import make_mesh
        from modal_examples_tpu.serving.engine import _shard_params

        mesh = make_mesh(
            {"tensor": args.tp}, devices=jax.devices()[: args.tp]
        )
        # Megatron specs, QuantizedWeight-aware (scales keep the output
        # dim's sharding) — the same placement the engine uses
        params = _shard_params(params, cfg, mesh)
    force(params)  # truly drain the build queue before timing anything
    weight_bytes = param_bytes(params)
    print(
        f"# {args.model} quant={args.quant} weights={weight_bytes/1e9:.2f} GB "
        f"build={time.time()-t0:.0f}s floor={weight_bytes/819e9*1e3:.1f} ms/step",
        file=sys.stderr,
    )

    K = args.steps

    # impls resolved ONCE here and passed explicitly — decode_step has no
    # env fallback (an env read at trace time is not part of any jit cache
    # key; ADVICE r3/r4)
    scatter_impl = os.environ.get("MTPU_SCATTER_IMPL", "xla")

    def make_block(impl):
        def block(params, k_pages, v_pages, prev, positions, tables, active,
                  key, temps, top_ps, top_ks, seeds):
            def body(carry, k_i):
                tok, pos, kp, vp = carry
                logits, kp, vp = llama.decode_step(
                    params, tok, pos, kp, vp, tables, active, cfg, impl=impl,
                    scatter_impl=scatter_impl, ragged_variant=args.variant,
                    mesh=mesh,
                )
                nxt = sample(
                    logits, k_i, temps, top_ps, top_ks, seeds=seeds,
                    step_ids=pos,
                )
                nxt = jnp.where(active, nxt, tok)
                return (nxt, pos + 1, kp, vp), nxt

            (last, _, k_pages, v_pages), toks = jax.lax.scan(
                body, (prev, positions, k_pages, v_pages),
                jax.random.split(key, K),
            )
            return toks, last, k_pages, v_pages

        return block

    for impl in args.impl.split(","):
        block = make_block(impl)
        for slots in [int(s) for s in args.slots.split(",")]:
            pp = args.max_len // args.page_size
            n_pages = 1 + slots * pp
            try:
                from modal_examples_tpu.ops import kv_empty

                cache_shape = (
                    cfg.n_layers, n_pages, args.page_size, cfg.n_kv_heads,
                    cfg.head_dim,
                )
                kv_dt = "int8" if args.kv_dtype == "int8" else jnp.bfloat16
                kp = kv_empty(cache_shape, kv_dt)
                vp = kv_empty(cache_shape, kv_dt)
                if mesh is not None:
                    # the ONE canonical kv-head cache placement, shared
                    # with engine._shard_cache
                    from modal_examples_tpu.ops import shard_cache_pages

                    kp, vp = shard_cache_pages(mesh, kp, vp)
                tables = jnp.asarray(
                    1 + np.arange(slots * pp).reshape(slots, pp), jnp.int32
                )
                positions = jnp.full((slots,), args.max_len // 2, jnp.int32)
                active = jnp.ones((slots,), bool)
                prev = jnp.zeros((slots,), jnp.int32)
                temps = jnp.ones((slots,), jnp.float32)
                top_ps = jnp.ones((slots,), jnp.float32)
                top_ks = jnp.zeros((slots,), jnp.int32)
                seeds = jnp.arange(slots, dtype=jnp.int32)
                fn = jax.jit(block, donate_argnums=(1, 2))
                t0 = time.time()
                toks, last, kp, vp = fn(
                    params, kp, vp, prev, positions, tables, active,
                    jax.random.PRNGKey(1), temps, top_ps, top_ks, seeds,
                )
                # NB: jax.block_until_ready is a NO-OP on the tunneled axon
                # backend (measured: returns in 0.03 ms while np.asarray on
                # the same value takes the full exec+RTT) — every forcing
                # point here must be a host fetch.
                np.asarray(last)
                compile_s = time.time() - t0

                def run(n):
                    nonlocal toks, last, kp, vp
                    t0 = time.time()
                    for i in range(n):
                        toks, last, kp, vp = fn(
                            params, kp, vp, last, positions, tables, active,
                            jax.random.PRNGKey(2 + i), temps, top_ps, top_ks,
                            seeds,
                        )
                    np.asarray(last)
                    return time.time() - t0

                # two-point slope: cancels the host->device round trip and
                # any fixed per-fetch cost the tunnel adds
                n1, n2 = max(2, args.iters // 3), args.iters
                t1, t2 = run(n1), run(n2)
                step_ms = (t2 - t1) / ((n2 - n1) * K) * 1e3
                print(
                    json.dumps(
                        {
                            "impl": impl,
                            # what actually ran, incl. the flat/grouped
                            # ragged formulation and kv dtype — the A/B
                            # lines must be attributable in captured logs
                            "plan": {
                                k: v
                                for k, v in llama.paged_impl_plan(
                                    cfg, args.page_size, impl, scatter_impl,
                                    kv_dtype=args.kv_dtype
                                    if args.kv_dtype == "int8"
                                    else "bfloat16",
                                    mesh=mesh,
                                    warn=False,
                                ).items()
                                if k != "downgraded"
                            } | (
                                {"ragged_variant": args.variant}
                                if args.variant else {}
                            ),
                            "slots": slots,
                            "kv_dtype": args.kv_dtype,
                            "step_ms": round(step_ms, 2),
                            "tok_s": round(slots / step_ms * 1e3, 1),
                            "floor_ms": round(weight_bytes / 819e9 * 1e3, 2),
                            # nbytes is a property on QuantizedKV and
                            # jax.Array alike (dtype-aware: int8 + scales)
                            "cache_gb": round((kp.nbytes + vp.nbytes) / 1e9, 3),
                            "compile_s": round(compile_s, 1),
                        }
                    ),
                    flush=True,
                )
                del kp, vp
            except Exception as e:  # OOM boundary is a *result* here
                print(
                    json.dumps(
                        {"impl": impl, "slots": slots,
                         "error": f"{type(e).__name__}: {str(e)[:200]}"}
                    ),
                    flush=True,
                )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
